"""Online serving layer: snapshots, recursive updates, bucketed batching.

Fast-tier coverage of serving/ (acceptance: the end-to-end flow below runs
on CPU, state parity vs a from-scratch re-filter at 1e-6 against the f64
NumPy oracle, and the no-recompile bucket bound holds):

- merged-DB fixture → load_snapshot → 5 online updates (one partially-NaN
  curve) → forecast h=12, with oracle parity for the filtered state,
- 50 mixed-shape requests compile at most ``lattice.n_programs`` programs
  (trace counters incremented inside the traced bodies),
- ``config.set_kalman_engine`` invalidates the serving caches (the
  tests/test_engines.py pattern extended to the serving builders).
"""

import dataclasses
import os

import jax.numpy as jnp
import numpy as np
import pytest

import yieldfactormodels_jl_tpu as yfm
from tests import oracle
from yieldfactormodels_jl_tpu import serving
from yieldfactormodels_jl_tpu.models.params import unpack_kalman
from yieldfactormodels_jl_tpu.ops.smoother import forward_moments
from yieldfactormodels_jl_tpu.persistence import database as db
from yieldfactormodels_jl_tpu.serving import batcher as sb
from yieldfactormodels_jl_tpu.serving import online as so

MATS = tuple(np.array([3, 12, 24, 60, 120, 240, 360]) / 12.0)
T_PANEL = 40
T_ORIGIN = 34  # snapshot origin: columns 0..33 conditioned, 34..39 arrive live


@pytest.fixture(scope="module")
def dns_setup():
    rng = np.random.default_rng(7)
    spec, _ = yfm.create_model("1C", MATS, float_type="float64")
    p = oracle.stable_1c_params(spec, np.float64)
    data = oracle.simulate_dns_panel(rng, np.asarray(MATS), T=T_PANEL)
    return spec, p, data


@pytest.fixture()
def merged_db(tmp_path, dns_setup):
    """A merged forecast DB holding fitted params for two tasks — the
    artifact the rolling-forecast pipeline leaves behind."""
    spec, p, data = dns_setup
    base = os.path.join(str(tmp_path), "db", "forecasts_expanding.sqlite3")
    dummy = np.zeros((2, 3))
    results = {k: dummy for k in ("preds", "factors", "states",
                                  "factor_loadings_1", "factor_loadings_2")}
    for task in (T_ORIGIN, T_ORIGIN - 2):
        db.save_oos_forecast_sharded(base, spec.model_string, "1", "expanding",
                                     task, results, loss=-1.0, params=p,
                                     forecast_horizon=2)
    return db.merge_forecast_shards(base, task_ids=[T_ORIGIN, T_ORIGIN - 2])


def _live_curves(data):
    """The five post-origin curves; the third is partially quoted."""
    curves = [data[:, t].copy() for t in range(T_ORIGIN, T_ORIGIN + 5)]
    curves[2][1] = np.nan
    curves[2][4] = np.nan
    return curves


def _oracle_state(spec, p, data, curves):
    """From-scratch f64 re-filter (predict → element-masked update) over the
    conditioning sample plus the live curves; returns final (β, P)."""
    kp = unpack_kalman(spec, jnp.asarray(p, dtype=jnp.float64))
    Z = np.asarray(oracle.dns_loadings(float(np.asarray(kp.gamma)[0]),
                                       np.asarray(MATS)))
    panel = np.concatenate([data[:, :T_ORIGIN], np.stack(curves, axis=1)],
                           axis=1)
    betas, Ps, _ = oracle.online_filter(
        Z, np.zeros(spec.N), np.asarray(kp.Phi), np.asarray(kp.delta),
        np.asarray(kp.Omega_state), float(kp.obs_var), panel)
    return betas[-1], Ps[-1]


# ---------------------------------------------------------------------------
# acceptance flow: merged DB → snapshot → updates (one partial) → forecast
# ---------------------------------------------------------------------------

def test_service_end_to_end_oracle_parity(dns_setup, merged_db):
    spec, p, data = dns_setup
    snap = serving.load_snapshot(merged_db, spec, T_ORIGIN, data)
    assert snap.meta.task_id == T_ORIGIN and snap.meta.n_obs == T_ORIGIN
    svc = serving.YieldCurveService(snap)
    # BOTH online engines ride the same 5 curves (incl. the partial one), so
    # the element-masked Potter update is pinned to the NumPy oracle too —
    # never to another JAX path alone (CLAUDE.md parity rule)
    svc_sqrt = serving.YieldCurveService(
        serving.load_snapshot(merged_db, spec, T_ORIGIN, data), engine="sqrt")

    curves = _live_curves(data)
    for k, y in enumerate(curves):
        ll = svc.update(date=T_ORIGIN + k, yields=y)
        assert np.isfinite(ll)
        np.testing.assert_allclose(svc_sqrt.update(T_ORIGIN + k, y), ll,
                                   rtol=1e-9)
    assert svc.version == 5 and svc.snapshot.meta.n_updates == 5

    beta_ref, P_ref = _oracle_state(spec, p, data, curves)
    np.testing.assert_allclose(np.asarray(svc.snapshot.beta), beta_ref,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(svc.snapshot.P), P_ref, atol=1e-6)
    np.testing.assert_allclose(np.asarray(svc_sqrt.snapshot.beta), beta_ref,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(svc_sqrt.snapshot.P), P_ref,
                               atol=1e-6)

    # h=12 forecast from the online state == oracle propagation of (β, P)
    fc = svc.forecast(12, quantiles=(0.1, 0.9))
    kp = unpack_kalman(spec, jnp.asarray(p, dtype=jnp.float64))
    Z = np.asarray(oracle.dns_loadings(float(np.asarray(kp.gamma)[0]),
                                       np.asarray(MATS)))
    Phi, delta = np.asarray(kp.Phi), np.asarray(kp.delta)
    Om, ov = np.asarray(kp.Omega_state), float(kp.obs_var)
    b, P = beta_ref.copy(), P_ref.copy()
    for h in range(12):
        b = delta + Phi @ b
        P = Phi @ P @ Phi.T + Om
        np.testing.assert_allclose(fc["means"][h], Z @ b, atol=1e-6)
        np.testing.assert_allclose(fc["covs"][h],
                                   Z @ P @ Z.T + ov * np.eye(spec.N),
                                   atol=1e-6)
    # quantiles bracket the mean and are ordered
    assert np.all(fc["quantiles"][0.1] < fc["means"])
    assert np.all(fc["means"] < fc["quantiles"][0.9])

    # stage latencies recorded for the ledger
    s = svc.latency_summary()
    assert s["update"]["count"] == 5 and s["forecast"]["count"] == 1
    assert s["update"]["p99"] >= s["update"]["p50"] > 0.0


def test_online_matches_library_refilter_and_sqrt_engine(dns_setup):
    """All-finite updates: the online chain continues the library filter
    exactly (f64, 1e-9), and the sqrt engine tracks it to 1e-6."""
    spec, p, data = dns_setup
    snap = serving.freeze_snapshot(spec, p, data, end=T_ORIGIN)
    services = {e: serving.YieldCurveService(
        serving.freeze_snapshot(spec, p, data, end=T_ORIGIN), engine=e)
        for e in serving.ONLINE_ENGINES}
    del snap
    for t in range(T_ORIGIN, T_PANEL):
        for svc in services.values():
            svc.update(t, data[:, t])
    _, outs = forward_moments(spec, jnp.asarray(p, dtype=jnp.float64),
                              jnp.asarray(data), 0, T_PANEL, "univariate")
    beta_ref = np.asarray(outs["beta_upd"][-1])
    P_ref = np.asarray(outs["P_upd"][-1])
    np.testing.assert_allclose(np.asarray(services["univariate"].snapshot.beta),
                               beta_ref, atol=1e-9)
    np.testing.assert_allclose(np.asarray(services["univariate"].snapshot.P),
                               P_ref, atol=1e-9)
    np.testing.assert_allclose(np.asarray(services["sqrt"].snapshot.beta),
                               beta_ref, atol=1e-6)
    np.testing.assert_allclose(np.asarray(services["sqrt"].snapshot.P),
                               P_ref, atol=1e-6)


@pytest.mark.parametrize("k", [4, 3])  # exact bucket and padded (3 → kb 4)
def test_update_k_equals_repeated_single_steps(dns_setup, k):
    """The k-bucketed catch-up program equals k single steps exactly —
    including when k pads up to the next K_BUCKET (padded steps must be
    true no-ops, not extra transitions)."""
    spec, p, data = dns_setup
    snap = serving.freeze_snapshot(spec, p, data, end=T_ORIGIN)
    params = jnp.asarray(p, dtype=jnp.float64)
    st = serving.OnlineState(snap.beta, snap.P)
    Y = data[:, T_ORIGIN:T_ORIGIN + k]
    st_k, lls, oks = serving.update_k(spec, params, st, Y)
    assert lls.shape == (k,) and bool(np.asarray(oks).all())
    st_1 = st
    for j in range(k):
        st_1, ll1, _ = serving.update(spec, params, st_1, Y[:, j])
        np.testing.assert_allclose(float(lls[j]), float(ll1), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(st_k.beta), np.asarray(st_1.beta),
                               rtol=1e-12)
    np.testing.assert_allclose(np.asarray(st_k.cov), np.asarray(st_1.cov),
                               rtol=1e-12, atol=1e-15)


@pytest.mark.parametrize("exact_jac", [False, True])
def test_online_tvl_matches_oracle(exact_jac):
    """The ``kalman_tvl`` branch of the online update (EKF: linearize ONCE at
    β_pred, fixed-linearization effective observation) is pinned to the
    independent NumPy oracle — never to another JAX path alone (CLAUDE.md
    parity rule).  Both online engines, both Jacobian variants, and the
    element-masked partial curve ride the same 5 live updates."""
    rng = np.random.default_rng(11)
    spec, _ = yfm.create_model("TVλ", MATS, float_type="float64")
    spec = dataclasses.replace(spec, exact_jacobian=exact_jac)
    p = oracle.stable_tvl_params(spec)
    data = oracle.simulate_dns_panel(rng, np.asarray(MATS), T=T_PANEL)
    curves = _live_curves(data)

    services = {e: serving.YieldCurveService(
        serving.freeze_snapshot(spec, p, data, end=T_ORIGIN), engine=e)
        for e in serving.ONLINE_ENGINES}
    lls = {e: [svc.update(T_ORIGIN + k, y) for k, y in enumerate(curves)]
           for e, svc in services.items()}

    kp = unpack_kalman(spec, jnp.asarray(p, dtype=jnp.float64))
    panel = np.concatenate([data[:, :T_ORIGIN], np.stack(curves, axis=1)],
                           axis=1)
    betas, Ps, lls_ref = oracle.online_filter_tvl(
        np.asarray(kp.Phi), np.asarray(kp.delta), np.asarray(kp.Omega_state),
        float(kp.obs_var), np.asarray(MATS), panel, exact_jacobian=exact_jac)
    for e, svc in services.items():
        np.testing.assert_allclose(np.asarray(svc.snapshot.beta), betas[-1],
                                   atol=1e-6, err_msg=e)
        np.testing.assert_allclose(np.asarray(svc.snapshot.P), Ps[-1],
                                   atol=1e-6, err_msg=e)
        np.testing.assert_allclose(lls[e], lls_ref[T_ORIGIN:], rtol=1e-6,
                                   atol=1e-9, err_msg=e)


def test_update_k_bucket_shares_programs(dns_setup):
    """Distinct gap lengths within one K_BUCKET share one compiled program."""
    spec, p, data = dns_setup
    snap = serving.freeze_snapshot(spec, p, data, end=T_ORIGIN)
    params = jnp.asarray(p, dtype=jnp.float64)
    st = serving.OnlineState(snap.beta, snap.P)
    serving.reset_trace_counts()
    for k in (5, 6, 7, 8):  # all bucket to kb=8
        serving.update_k(spec, params, st, data[:, T_ORIGIN:T_ORIGIN + k])
    assert serving.trace_counts["update_k"] <= 1, \
        dict(serving.trace_counts)


def test_update_failure_is_structured_error_and_rolls_back(dns_setup):
    """Non-PD innovation chain → NaN sentinel inside the kernel → structured
    ServingError at the driver, with the last good snapshot retained."""
    spec, p, data = dns_setup
    bad = np.asarray(p, dtype=np.float64).copy()
    bad[spec.layout["obs_var"][0]] = -10.0  # f = zPz + σ² < 0 ⇒ ok=False
    snap = serving.freeze_snapshot(spec, p, data, end=T_ORIGIN)
    svc = serving.YieldCurveService(dataclasses.replace(
        snap, params=jnp.asarray(bad)))
    v0, beta0 = svc.version, np.asarray(svc.snapshot.beta).copy()
    with pytest.raises(serving.ServingError) as ei:
        svc.update(0, data[:, T_ORIGIN])
    assert ei.value.stage == "update" and ei.value.context["version"] == v0
    assert svc.version == v0  # rolled back: no NaN state escapes the driver
    np.testing.assert_array_equal(np.asarray(svc.snapshot.beta), beta0)


def test_freeze_failure_raises_loudly(dns_setup):
    spec, p, data = dns_setup
    bad = np.asarray(p, dtype=np.float64).copy()
    lo, hi = spec.layout["phi"]
    bad[lo:hi] = (1.05 * np.eye(spec.state_dim)).reshape(-1)  # explosive
    with pytest.raises(serving.ServingError):
        serving.freeze_snapshot(spec, bad, data, engine="joint")


def test_registry_bulk_load_one_query(dns_setup, merged_db):
    spec, p, data = dns_setup
    params_by_task = db.read_all_task_params(merged_db)
    assert sorted(params_by_task) == [T_ORIGIN - 2, T_ORIGIN]
    for task_id, params in params_by_task.items():
        np.testing.assert_array_equal(params,
                                      db.read_task_params(merged_db, task_id))
    reg = serving.SnapshotRegistry()
    keys = reg.load_all(merged_db, spec, data)
    assert len(reg) == 2 and keys == reg.keys()
    s1 = reg.get(spec.model_string, T_ORIGIN)
    s2 = reg.get(spec.model_string, T_ORIGIN - 2)
    assert s1.meta.n_obs == T_ORIGIN and s2.meta.n_obs == T_ORIGIN - 2
    assert not np.allclose(np.asarray(s1.beta), np.asarray(s2.beta))
    with pytest.raises(serving.ServingError):
        reg.get(spec.model_string, 999)


def test_registry_quarantines_malformed_params_row(dns_setup, merged_db):
    """A corrupt/wrong-shape params blob must not take the bulk boot down:
    the row is skipped with its error recorded, healthy tasks register."""
    import sqlite3

    spec, p, data = dns_setup
    con = sqlite3.connect(merged_db)
    con.execute(
        "INSERT OR REPLACE INTO forecasts("
        "model,thread,window,task_id,loss,params,preds,fl1,fl2,factors,states"
        ") VALUES(?,?,?,?,?,?,?,?,?,?,?)",
        (spec.model_string, "1", "expanding", 5, -1.0,
         db.ser(np.zeros(3)),  # wrong length for this spec
         *[db.ser(np.zeros((1, 1)))] * 5))
    con.commit()
    con.close()
    reg = serving.SnapshotRegistry()
    keys = reg.load_all(merged_db, spec, data)
    assert len(keys) == 2 and len(reg) == 2  # the two healthy tasks
    assert list(reg.last_errors) == [5]


def test_registry_batched_load_matches_serial_and_compiles_once(
        tmp_path, dns_setup):
    """ISSUE satellite: ``load_all`` freezes every task through ONE vmapped
    filter pass (snapshot._jitted_freeze_batch) instead of a per-task serial
    loop that compiles once per distinct window end.  Pins: (a) the batched
    snapshots equal the serial ones to f64 roundoff, (b) one warm boot =
    one freeze trace regardless of how many distinct ends the DB holds, and
    (c) the measured warm-boot wall does not regress vs the serial loop
    (the serial path pays one compile per end)."""
    import time

    from yieldfactormodels_jl_tpu.serving import snapshot as ssnap

    spec, p, data = dns_setup
    base = os.path.join(str(tmp_path), "db", "forecasts_expanding.sqlite3")
    dummy = np.zeros((2, 3))
    results = {k: dummy for k in ("preds", "factors", "states",
                                  "factor_loadings_1", "factor_loadings_2")}
    # six tasks with six DISTINCT window ends — same-shape grouping would
    # batch none of them; the causal full-pass trick batches all six
    task_ids = [T_ORIGIN - 2 * i for i in range(6)]
    for task in task_ids:
        db.save_oos_forecast_sharded(base, spec.model_string, "1",
                                     "expanding", task, results, loss=-1.0,
                                     params=p, forecast_horizon=2)
    merged = db.merge_forecast_shards(base, task_ids=task_ids)

    ssnap._jitted_freeze_batch.cache_clear()
    reg_serial = serving.SnapshotRegistry()
    t0 = time.perf_counter()
    keys_serial = reg_serial.load_all(merged, spec, data, batch=False)
    t_serial = time.perf_counter() - t0

    reg_batch = serving.SnapshotRegistry()
    t0 = time.perf_counter()
    keys_batch = reg_batch.load_all(merged, spec, data)
    t_batch = time.perf_counter() - t0

    assert keys_batch == keys_serial and len(keys_batch) == 6
    for key in keys_batch:
        a, b = reg_batch.get(*key), reg_serial.get(*key)
        assert a.meta == b.meta
        np.testing.assert_allclose(np.asarray(a.beta), np.asarray(b.beta),
                                   rtol=1e-12, atol=1e-12)
        np.testing.assert_allclose(np.asarray(a.P), np.asarray(b.P),
                                   rtol=1e-12, atol=1e-12)
    # warm-boot wall: serial pays ~6 compiles, the batch pays 1 — the batch
    # must not be slower (generous factor: timing on a contended CPU box)
    assert t_batch < 1.5 * t_serial, (t_batch, t_serial)
    # ...and a second boot reuses the cached program entirely
    assert ssnap._jitted_freeze_batch.cache_info().currsize == 1


def test_registry_thread_safety_put_get_hammer(dns_setup):
    """ISSUE satellite: ``put``/``get``/``keys`` hammered from two threads —
    the gateway worker and the health-rebuild path share one registry; no
    exception may escape and every completed put must be readable."""
    import threading

    spec, p, data = dns_setup
    snap = serving.freeze_snapshot(spec, p, data, end=T_ORIGIN)
    reg = serving.SnapshotRegistry()
    errors, done = [], threading.Event()

    def writer():
        try:
            for i in range(300):
                reg.put(dataclasses.replace(
                    snap, meta=dataclasses.replace(snap.meta, task_id=i)))
        except Exception as e:  # noqa: BLE001
            errors.append(e)
        finally:
            done.set()

    def reader():
        try:
            while not done.is_set() or len(reg) < 300:
                for key in reg.keys():
                    reg.get(*key)  # must never see a half-written entry
                if errors:
                    return
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=writer),
               threading.Thread(target=reader)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert len(reg) == 300
    assert reg.get(spec.model_string, 299).meta.task_id == 299


def test_shared_batcher_banks_other_submitters_results(dns_setup):
    """A service flushing a SHARED batcher must not drop another submitter's
    pending results — they stay banked until collected by ticket."""
    spec, p, data = dns_setup
    lattice = serving.BucketLattice(horizons=(4,), batch_sizes=(1, 4),
                                    scenario_counts=(4,))
    m = serving.MicroBatcher(lattice)
    snap_b = serving.freeze_snapshot(spec, p, data, end=T_ORIGIN - 2)
    svc = serving.YieldCurveService(
        serving.freeze_snapshot(spec, p, data, end=T_ORIGIN),
        batcher=m)
    tb = m.submit(snap_b, serving.ForecastRequest(3))
    fc = svc.forecast(4)          # flushes tb too
    assert fc["means"].shape == (4, spec.N)
    out_b = m.result(tb)          # banked, still collectible
    assert out_b["means"].shape == (3, spec.N)
    with pytest.raises(serving.ServingError):
        m.result(tb)              # collect-once


def test_failed_bucket_error_carries_request_stage(dns_setup):
    """A failed scenario ticket surfaces as ``stage="scenarios"`` and a
    failed forecast chunk as ``stage="forecast"`` — callers dispatch on
    ``err.stage`` (the documented vocabulary in ServingError)."""
    spec, p, data = dns_setup
    lattice = serving.BucketLattice(horizons=(4,), batch_sizes=(1,),
                                    scenario_counts=(4,))
    m = serving.MicroBatcher(lattice)
    snap = serving.freeze_snapshot(spec, p, data, end=T_ORIGIN)
    bad = dataclasses.replace(snap, params=snap.params[:3])  # unpack blows up
    ts = m.submit(bad, serving.ScenarioRequest(4, 4))
    tf = m.submit(bad, serving.ForecastRequest(4))
    m.flush()
    with pytest.raises(serving.ServingError) as ei:
        m.result(ts)
    assert ei.value.stage == "scenarios"
    with pytest.raises(serving.ServingError) as ei:
        m.result(tf)
    assert ei.value.stage == "forecast"


def test_flush_nonfinite_ticket_degrades_alone(dns_setup):
    """Partial-failure isolation (DESIGN §12): a NaN-state snapshot riding a
    healthy bucket chunk yields a per-ticket DEGRADED result; the other
    tickets in the same padded program return normally."""
    spec, p, data = dns_setup
    lattice = serving.BucketLattice(horizons=(4,), batch_sizes=(1, 4),
                                    scenario_counts=(4,))
    m = serving.MicroBatcher(lattice)
    good = serving.freeze_snapshot(spec, p, data, end=T_ORIGIN)
    bad = dataclasses.replace(good, beta=jnp.full_like(good.beta, jnp.nan))
    t1 = m.submit(good, serving.ForecastRequest(4))
    t2 = m.submit(bad, serving.ForecastRequest(4))
    t3 = m.submit(good, serving.ForecastRequest(4))
    ts = m.submit(bad, serving.ScenarioRequest(4, 4))
    m.flush()
    r1, r2, r3, rs = m.result(t1), m.result(t2), m.result(t3), m.result(ts)
    for r in (r1, r3):  # same chunk as the poisoned ticket, unharmed
        assert "degraded" not in r and np.all(np.isfinite(r["means"]))
    assert r2["degraded"] and not np.all(np.isfinite(r2["means"]))
    assert rs["degraded"] and rs["stage"] == "scenarios"
    np.testing.assert_array_equal(r1["means"], r3["means"])


def test_flush_chunk_exception_isolated_per_ticket(dns_setup):
    """A request whose padded program RAISES (malformed params) is re-run
    alone: only its ticket errors, chunk-mates still answer."""
    spec, p, data = dns_setup
    lattice = serving.BucketLattice(horizons=(4,), batch_sizes=(1, 4),
                                    scenario_counts=(4,))
    m = serving.MicroBatcher(lattice)
    good = serving.freeze_snapshot(spec, p, data, end=T_ORIGIN)
    bad = dataclasses.replace(good, params=good.params[:3])  # unpack blows up
    t1 = m.submit(good, serving.ForecastRequest(4))
    t2 = m.submit(bad, serving.ForecastRequest(4))
    t3 = m.submit(good, serving.ForecastRequest(4))
    m.flush()
    assert np.all(np.isfinite(m.result(t1)["means"]))
    with pytest.raises(serving.ServingError) as ei:
        m.result(t2)
    assert ei.value.stage == "forecast"
    assert np.all(np.isfinite(m.result(t3)["means"]))


def test_flush_chaos_seam_degrades_one_ticket(dns_setup):
    """The ``poison_ticket`` chaos seam marks exactly the N-th flushed ticket
    degraded — the drill for the isolation path without crafting NaNs."""
    from yieldfactormodels_jl_tpu.orchestration import chaos

    spec, p, data = dns_setup
    lattice = serving.BucketLattice(horizons=(4,), batch_sizes=(1, 4),
                                    scenario_counts=(4,))
    m = serving.MicroBatcher(lattice)
    snap = serving.freeze_snapshot(spec, p, data, end=T_ORIGIN)
    tickets = [m.submit(snap, serving.ForecastRequest(4)) for _ in range(3)]
    chaos.configure("poison_ticket:@2")
    try:
        m.flush()
    finally:
        chaos.reset()
    outs = [m.result(t) for t in tickets]
    assert [bool(o.get("degraded")) for o in outs] == [False, True, False]
    # the degraded ticket still carries its (finite) result — policy is the
    # driver's call (service heals, gateway answers from last-good)
    assert np.all(np.isfinite(outs[1]["means"]))


def test_scenarios_match_predictive_moments(dns_setup):
    """Scenario draws are distributed per the predictive density, pinned to
    an independent NumPy (δ, Φ, Ω) moment recursion — never to another JAX
    path alone (CLAUDE.md parity rule).  The served density must equal the
    NumPy moments tightly; the MC mean matches them loosely (seeded)."""
    spec, p, data = dns_setup
    svc = serving.YieldCurveService(
        serving.freeze_snapshot(spec, p, data, end=T_ORIGIN),
        lattice=serving.BucketLattice(horizons=(4,), batch_sizes=(1,),
                                      scenario_counts=(256,)))
    fc = svc.forecast(4)
    sc = svc.scenarios(n=256, h=4, seed=3)
    assert sc["paths"].shape == (spec.N, 4, 256)

    kp = unpack_kalman(spec, jnp.asarray(p, dtype=jnp.float64))
    Z = np.asarray(oracle.dns_loadings(float(np.asarray(kp.gamma)[0]),
                                       np.asarray(MATS)))
    Phi, delta = np.asarray(kp.Phi), np.asarray(kp.delta)
    Om, ov = np.asarray(kp.Omega_state), float(kp.obs_var)
    b = np.asarray(svc.snapshot.beta, dtype=np.float64)
    P = np.asarray(svc.snapshot.P, dtype=np.float64)
    means, sds = [], []
    for _ in range(4):
        b = delta + Phi @ b
        P = Phi @ P @ Phi.T + Om
        means.append(Z @ b)
        sds.append(np.sqrt(np.diag(Z @ P @ Z.T) + ov))
    means, sds = np.stack(means), np.stack(sds)
    np.testing.assert_allclose(fc["means"], means, rtol=1e-8, atol=1e-10)
    mc_mean = sc["paths"].mean(axis=-1).T  # (4, N)
    assert np.all(np.abs(mc_mean - means) < 5 * sds / np.sqrt(256) + 1e-9)


# ---------------------------------------------------------------------------
# no-recompile bucketing + engine-cache invalidation
# ---------------------------------------------------------------------------

def test_no_recompile_50_mixed_requests(dns_setup):
    """50 heterogeneous requests (random horizons, scenario counts, across
    two snapshots) trigger at most ``lattice.n_programs`` compilations."""
    spec, p, data = dns_setup
    lattice = serving.BucketLattice(horizons=(4, 8), batch_sizes=(1, 4),
                                    scenario_counts=(4,))
    snap_a = serving.freeze_snapshot(spec, p, data, end=T_ORIGIN)
    snap_b = serving.freeze_snapshot(spec, p, data, end=T_ORIGIN - 2)
    m = serving.MicroBatcher(lattice)

    serving.reset_trace_counts()
    rng = np.random.default_rng(0)
    tickets = []
    for batch in range(10):  # 10 flushes × 5 requests = 50
        for j in range(5):
            snap = snap_a if (batch + j) % 2 else snap_b
            if j == 4 and batch % 3 == 0:
                req = serving.ScenarioRequest(n=int(rng.integers(1, 5)),
                                              horizon=int(rng.integers(1, 9)),
                                              seed=j)
            else:
                req = serving.ForecastRequest(int(rng.integers(1, 9)))
            tickets.append(m.submit(snap, req))
        res = m.flush()
        assert len(res) == 5
        for r in res.values():
            key = "means" if "means" in r else "paths"
            assert np.all(np.isfinite(r[key]))
    n_compiles = sum(serving.trace_counts.values())
    assert 0 < n_compiles <= lattice.n_programs, (
        f"{n_compiles} compilations for 50 requests exceeds the lattice "
        f"bound {lattice.n_programs}: {dict(serving.trace_counts)}")

    # the same mix again is compile-free
    before = sum(serving.trace_counts.values())
    for j in range(5):
        m.submit(snap_a if j % 2 else snap_b,
                 serving.ForecastRequest(int(rng.integers(1, 9))))
    m.flush()
    assert sum(serving.trace_counts.values()) == before


def test_oversized_request_rejected_at_submit(dns_setup):
    spec, p, data = dns_setup
    lattice = serving.BucketLattice(horizons=(4,), batch_sizes=(1,),
                                    scenario_counts=(4,))
    snap = serving.freeze_snapshot(spec, p, data, end=T_ORIGIN)
    m = serving.MicroBatcher(lattice)
    with pytest.raises(serving.ServingError):
        m.submit(snap, serving.ForecastRequest(5))
    with pytest.raises(serving.ServingError):
        m.submit(snap, serving.ScenarioRequest(n=5, horizon=4))
    # non-positive sizes must error, not round up and return truncated junk
    for bad in (serving.ForecastRequest(0), serving.ForecastRequest(-2),
                serving.ScenarioRequest(n=0, horizon=4),
                serving.ScenarioRequest(n=4, horizon=0)):
        with pytest.raises(serving.ServingError):
            m.submit(snap, bad)
    assert len(m) == 0


def test_banked_results_are_bounded(dns_setup):
    """Orphaned tickets (submitter never collects) evict oldest-first at
    ``max_banked`` — no unbounded growth in a long-lived process."""
    spec, p, data = dns_setup
    lattice = serving.BucketLattice(horizons=(4,), batch_sizes=(1, 4),
                                    scenario_counts=(4,))
    snap = serving.freeze_snapshot(spec, p, data, end=T_ORIGIN)
    m = serving.MicroBatcher(lattice, max_banked=3)
    tickets = [m.submit(snap, serving.ForecastRequest(4)) for _ in range(5)]
    m.flush()
    assert len(m._done) == 3
    for t in tickets[:2]:  # evicted
        with pytest.raises(serving.ServingError):
            m.result(t)
    for t in tickets[2:]:  # retained, newest
        assert m.result(t)["means"].shape == (4, spec.N)


def test_engine_switch_invalidates_serving_caches(dns_setup):
    """set_kalman_engine must clear the serving trace-time builders too —
    the estimation-layer invalidation contract (tests/test_engines.py)
    extended to serving."""
    spec, p, data = dns_setup
    snap = serving.freeze_snapshot(spec, p, data, end=T_ORIGIN)
    svc = serving.YieldCurveService(
        snap, lattice=serving.BucketLattice(horizons=(4,), batch_sizes=(1,),
                                            scenario_counts=(4,)))
    svc.update(0, data[:, T_ORIGIN])
    svc.forecast(4)
    svc.scenarios(n=4, h=4)
    builders = (so._jitted_update, so._jitted_update_k, so._jitted_scenarios,
                sb._jitted_forecast_bucket)
    populated = [b for b in builders if b.cache_info().currsize]
    assert so._jitted_update in populated
    assert sb._jitted_forecast_bucket in populated
    try:
        yfm.set_kalman_engine("sqrt")
        for b in builders:
            assert b.cache_info().currsize == 0, b
    finally:
        yfm.set_kalman_engine("univariate")


def test_warmup_empty_axes_mean_none_not_all(dns_setup):
    """An explicit EMPTY warmup axis means "none of these", never "the whole
    lattice" (the falsy-container trap): scenario-only warmup must not trace
    any forecast program, and ``horizons=()`` pre-traces nothing."""
    spec, p, data = dns_setup
    # bucket values unused elsewhere in this module, so the trace counters
    # see fresh compilations (shared lru caches persist across tests)
    lattice = serving.BucketLattice(horizons=(5,), batch_sizes=(2,),
                                    scenario_counts=(3,))
    snap = serving.freeze_snapshot(spec, p, data, end=T_ORIGIN)
    m = serving.MicroBatcher(lattice)
    serving.reset_trace_counts()
    n = m.warmup(snap, batch_sizes=(), scenario_counts=(3,))
    assert n == 1 and serving.trace_counts["scenarios"] == 1
    assert serving.trace_counts["forecast"] == 0
    assert m.warmup(snap, horizons=()) == 0


def test_warmup_pretraces_then_serving_is_compile_free(dns_setup):
    spec, p, data = dns_setup
    lattice = serving.BucketLattice(horizons=(4, 8), batch_sizes=(1,),
                                    scenario_counts=(4,))
    svc = serving.YieldCurveService(
        serving.freeze_snapshot(spec, p, data, end=T_ORIGIN), lattice=lattice)
    svc.warmup(scenario_counts=(4,))
    serving.reset_trace_counts()
    svc.update(0, data[:, T_ORIGIN])
    svc.forecast(7)
    svc.scenarios(n=3, h=4)
    assert sum(serving.trace_counts.values()) == 0, \
        dict(serving.trace_counts)
