"""Doc-rot guard: every import and API name QUICKSTART.md shows must exist.

The snippets carry placeholders (``np.load(...)``) so they are not exec'd;
instead each ``import``/``from`` line is imported for real and every
``module.attr`` reference against a known module alias is getattr-checked.
"""

import importlib
import os
import re

DOC = os.path.join(os.path.dirname(__file__), os.pardir, "docs", "QUICKSTART.md")

# doc alias -> importable module path
ALIASES = {
    "yfm": "yieldfactormodels_jl_tpu",
    "config": "yieldfactormodels_jl_tpu.config",
    "optimize": "yieldfactormodels_jl_tpu.estimation.optimize",
    "mesh": "yieldfactormodels_jl_tpu.parallel.mesh",
    "smoother": "yieldfactormodels_jl_tpu.ops.smoother",
    "pallas_kf": "yieldfactormodels_jl_tpu.ops.pallas_kf",
    "api": "yieldfactormodels_jl_tpu.models.api",
}


def _code_lines():
    text = open(DOC).read()
    blocks = re.findall(r"```python\n(.*?)```", text, flags=re.S)
    for block in blocks:
        for line in block.splitlines():
            yield line


def test_quickstart_imports_resolve():
    matched = 0
    for line in _code_lines():
        line = line.strip()
        m = re.match(r"from ([\w.]+) import \(?([\w, ]+)\)?$", line)
        if m:
            matched += 1
            mod = importlib.import_module(m.group(1))
            for name in m.group(2).split(","):
                assert hasattr(mod, name.strip()), (line, name)
            continue
        m = re.match(r"import ([\w.]+)(?: as \w+)?$", line)
        if m:
            matched += 1
            if m.group(1) not in ("numpy", "jax", "jax.numpy"):
                importlib.import_module(m.group(1))
    # vacuity guard: the doc currently shows well over 5 import lines; if the
    # regexes rot (or the doc stops matching), fail instead of green-lighting
    assert matched >= 5, f"only {matched} import lines matched — regex/doc drift"


def test_quickstart_attr_references_resolve():
    pat = re.compile(r"\b(%s)\.(\w+)" % "|".join(ALIASES))
    seen = set()
    for line in _code_lines():
        if line.strip().startswith("#"):
            continue
        for alias, attr in pat.findall(line):
            seen.add((alias, attr))
    assert seen, "no attr references found — regex or doc drifted"
    for alias, attr in sorted(seen):
        mod = importlib.import_module(ALIASES[alias])
        assert hasattr(mod, attr), f"{ALIASES[alias]}.{attr} shown in QUICKSTART but missing"
