"""Square-root (Potter) Kalman kernel vs the univariate production path."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from yieldfactormodels_jl_tpu import create_model
from yieldfactormodels_jl_tpu.ops import sqrt_kf, univariate_kf

MATS = tuple(np.array([3, 6, 12, 24, 36, 60, 84, 120, 180, 240, 360]) / 12.0)


def _params(spec, rng, dtype=np.float64):
    p = np.zeros(spec.n_params, dtype=dtype)
    if "gamma" in spec.layout:
        lo, hi = spec.layout["gamma"]
        p[lo:hi] = np.log(0.45)
    lo, hi = spec.layout["obs_var"]
    p[lo:hi] = 4e-4
    Ms = spec.state_dim
    k = spec.layout["chol"][0]
    for j in range(Ms):
        for i in range(j + 1):
            p[k] = 0.05 if i == j else 0.004
            k += 1
    lo, hi = spec.layout["delta"]
    p[lo:hi] = 0.1 * rng.standard_normal(Ms)
    lo, hi = spec.layout["phi"]
    p[lo:hi] = (0.92 * np.eye(Ms)).reshape(-1)
    return p


@pytest.mark.parametrize("code", ["1C", "TVλ", "AFNS5"])
def test_matches_univariate_f64(code, rng):
    spec, _ = create_model(code, MATS, float_type="float64")
    p = jnp.asarray(_params(spec, rng))
    data = jnp.asarray(0.4 * rng.standard_normal((len(MATS), 60)) + 4.0)
    ref = float(univariate_kf.get_loss(spec, p, data, 1, 58))
    got = float(sqrt_kf.get_loss(spec, p, data, 1, 58))
    assert np.isfinite(ref)
    np.testing.assert_allclose(got, ref, rtol=1e-8)


def test_nan_and_window_conventions(rng):
    spec, _ = create_model("1C", MATS, float_type="float64")
    p = jnp.asarray(_params(spec, rng))
    data = 0.4 * rng.standard_normal((len(MATS), 50)) + 4.0
    data[:, -5:] = np.nan
    data[3, 7] = np.nan
    ref = float(univariate_kf.get_loss(spec, jnp.asarray(p), jnp.asarray(data)))
    got = float(sqrt_kf.get_loss(spec, jnp.asarray(p), jnp.asarray(data)))
    np.testing.assert_allclose(got, ref, rtol=1e-8)


def test_f32_stays_finite_on_long_stiff_panel(rng):
    """The PSD-by-construction property: tiny obs noise + long f32 recursion.

    With obs_var ~1e-8 the plain rank-1 downdates lose PSD-ness in f32 far
    more easily; the square-root form must stay finite and close to the f64
    truth.
    """
    spec64, _ = create_model("1C", MATS, float_type="float64")
    spec32, _ = create_model("1C", MATS, float_type="float32")
    p = _params(spec64, rng)
    lo, hi = spec64.layout["obs_var"]
    p[lo:hi] = 1e-8
    data = 0.4 * rng.standard_normal((len(MATS), 400)) + 4.0
    truth = float(univariate_kf.get_loss(spec64, jnp.asarray(p), jnp.asarray(data)))
    got32 = float(sqrt_kf.get_loss(
        spec32, jnp.asarray(p, dtype=jnp.float32),
        jnp.asarray(data, dtype=jnp.float32)))
    assert np.isfinite(truth)
    assert np.isfinite(got32)
    assert abs(got32 - truth) / abs(truth) < 5e-3


def test_grad_flows_through_sqrt_kernel(rng):
    spec, _ = create_model("1C", MATS, float_type="float64")
    p = jnp.asarray(_params(spec, rng))
    data = jnp.asarray(0.4 * rng.standard_normal((len(MATS), 30)) + 4.0)
    g = jax.grad(lambda q: sqrt_kf.get_loss(spec, q, data))(p)
    assert np.all(np.isfinite(np.asarray(g)))
    assert np.any(np.asarray(g) != 0)
