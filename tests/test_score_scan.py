"""Score-tree engine (ops/score_scan.py, docs/DESIGN.md §19) acceptance.

Oracle-backed parity of the ``"score_tree"`` MSED engine against the
independent NumPy loops (tests/oracle.linearized_score_filter — central-FD
surrogate Jacobians + sequential affine recursion, a DIFFERENT algebraic
route than the engine's ``jacfwd`` elements + combine tree), the fixed-point
contract against the sequential ``"scan"`` recursion
(models/score_driven.py), NaN-panel/window semantics, K-sweep convergence
monotonicity, grad parity (the tree is differentiated end-to-end — the
deliberate no-stop_gradient divergence from the SLR engine), trace counters,
the introspection seam (config.engines_for / tree_engine_for) with the api
dispatch and its K=1-only gate, the ladder's score_tree rescue rung, and the
time-sharded objective's shard-aligned-chunk bit-parity.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import yieldfactormodels_jl_tpu as yfm
from tests import oracle
from yieldfactormodels_jl_tpu import config
from yieldfactormodels_jl_tpu.models import api
from yieldfactormodels_jl_tpu.models import score_driven as sd
from yieldfactormodels_jl_tpu.models.params import untransform_params
from yieldfactormodels_jl_tpu.ops import score_scan
from yieldfactormodels_jl_tpu.robustness import ladder, taxonomy as tax

MATS = tuple(np.array([3, 12, 24, 60, 120, 240, 360]) / 12.0)


def _msed_case(rng, T=160, code="SD-NS"):
    spec, _ = yfm.create_model(code, MATS, float_type="float64")
    p = oracle.stable_msed_params(spec)
    data = oracle.simulate_dns_panel(rng, np.asarray(MATS), T=T, lam=0.5)
    return spec, p, np.asarray(data, dtype=np.float64)


def _struct(spec, p):
    """The oracle's parameter dict (msedriven/paramteroperations.jl layout:
    A, B unless random-walk, ω, δ, col-major Φ)."""
    if spec.random_walk:
        return {"A": np.array([p[0]]), "B": None, "omega": np.array([p[1]]),
                "delta": p[2:5], "Phi": p[5:14].reshape(3, 3).T}
    return {"A": np.array([p[0]]), "B": np.array([p[1]]),
            "omega": np.array([p[2]]), "delta": p[3:6],
            "Phi": p[6:15].reshape(3, 3).T}


# ---------------------------------------------------------------------------
# the introspection seam (config.engines_for) and registries
# ---------------------------------------------------------------------------

def test_engine_registries_and_applicability():
    """"score_tree" is a first-class MSED_ENGINES entry and
    engines_for/tree_engine_for agree with the capability flag
    (spec.supports_score_tree: plain-gradient specs only — the EWMA
    scale_grad lineage keeps the sequential scan)."""
    assert config.MSED_ENGINES == ("scan", "score_tree")
    sdns, _ = yfm.create_model("SD-NS", MATS, float_type="float64")
    rwsd, _ = yfm.create_model("RWSD-NS", MATS, float_type="float64")
    ssd, _ = yfm.create_model("SSD-NS", MATS, float_type="float64")
    assert sdns.supports_score_tree and rwsd.supports_score_tree
    assert not ssd.supports_score_tree
    assert config.engines_for(sdns) == config.MSED_ENGINES
    assert config.engines_for(rwsd) == config.MSED_ENGINES
    assert config.engines_for(ssd) == ("scan",)
    assert config.tree_engine_for(sdns) == "score_tree"
    assert config.tree_engine_for(rwsd) == "score_tree"
    assert config.tree_engine_for(ssd) is None


def test_api_dispatch_validation_consults_engines_for(rng):
    """Explicit engine= outside engines_for(spec) raises naming the valid
    set; K-replay losses cannot ride the tree (K >= 2 CONTINUES the
    sequential recursion — no tree semantics) and the gate is loud."""
    spec, p, data = _msed_case(rng, T=60)
    dj, pj = jnp.asarray(data), jnp.asarray(p)
    ssd, _ = yfm.create_model("SSD-NS", MATS, float_type="float64")
    with pytest.raises(ValueError, match="engines_for"):
        api.get_loss(ssd, jnp.zeros(ssd.n_params), dj, engine="score_tree")
    with pytest.raises(ValueError, match="K=1"):
        api.get_loss(spec, pj, dj, K=2, engine="score_tree")
    a = float(api.get_loss(spec, pj, dj, engine="scan"))
    b = float(api.get_loss(spec, pj, dj))
    np.testing.assert_allclose(a, b, rtol=1e-12)


def test_t_switch_upgrades_msed_to_score_tree(rng, monkeypatch):
    spec, p, data = _msed_case(rng, T=100)
    dj, pj = jnp.asarray(data), jnp.asarray(p)
    calls = []
    real = score_scan.get_loss
    monkeypatch.setattr(score_scan, "get_loss",
                        lambda *a, **k: calls.append(1) or real(*a, **k))
    try:
        config.set_loglik_t_switch(64)
        api.get_loss(spec, pj, dj)                   # T=100 >= 64 → tree
        assert len(calls) == 1
        api.get_loss(spec, pj, dj[:, :50])           # short → sequential
        assert len(calls) == 1
        api.get_loss(spec, pj, dj, engine="scan")    # explicit wins
        assert len(calls) == 1
        api.get_loss(spec, pj, dj, K=2)              # K-replay stays scan
        assert len(calls) == 1
        ssd, _ = yfm.create_model("SSD-NS", MATS, float_type="float64")
        with np.errstate(all="ignore"):              # not capable → scan
            api.get_loss(ssd, jnp.zeros(ssd.n_params), dj)
        assert len(calls) == 1
    finally:
        config.set_loglik_t_switch(0)


# ---------------------------------------------------------------------------
# oracle parity — the iterated semantics AND the sequential fixed point
# ---------------------------------------------------------------------------

def test_score_tree_single_chunk_is_sequential(rng):
    """One chunk covering the panel + one sweep IS the sequential recursion
    (pass B replays every step from the exact start state) — float-rounding
    parity against models/score_driven.get_loss."""
    spec, p, data = _msed_case(rng, T=160)
    dj, pj = jnp.asarray(data), jnp.asarray(p)
    seq = float(sd.get_loss(spec, pj, dj))
    one = float(score_scan.get_loss(spec, pj, dj, sweeps=1, chunk=160))
    np.testing.assert_allclose(one, seq, rtol=1e-12)


@pytest.mark.parametrize("sweeps", [1, 2, 3])
def test_score_tree_oracle_parity_iterated_semantics(sweeps, rng):
    """Engine vs tests/oracle.linearized_score_filter at MATCHING (sweeps,
    chunk) — pins the iterated two-scale semantics themselves (composed
    FD-linearized affine surrogates + chunked true-recursion refinement with
    the Jacobi entry shift), not just the fixed point, at an adversarially
    small chunk where intermediate sweeps still differ from the sequential
    scan.  Loss AND the post-transition state trajectories."""
    spec, p, data = _msed_case(rng, T=160)
    preds_o, g_o, b_o = oracle.linearized_score_filter(
        _struct(spec, p), np.asarray(MATS), data, sweeps=sweeps, chunk=32)
    want = oracle.msed_loss_from_preds(preds_o, data)
    got = float(score_scan.get_loss(spec, jnp.asarray(p), jnp.asarray(data),
                                    sweeps=sweeps, chunk=32))
    np.testing.assert_allclose(got, want, rtol=1e-12)
    g_e, b_e = score_scan.filter_states(spec, jnp.asarray(p),
                                        jnp.asarray(data), sweeps=sweeps,
                                        chunk=32)
    np.testing.assert_allclose(np.asarray(g_e), g_o, atol=1e-10)
    np.testing.assert_allclose(np.asarray(b_e), b_o, atol=1e-10)


def test_score_tree_matches_sequential_fixed_point(rng):
    """The engine at its DEFAULTS against the sequential scan on a
    multi-chunk panel: K=2 at parity tolerance, one extra sweep tightening
    it by orders of magnitude (the ≈B^L contraction)."""
    spec, p, data = _msed_case(rng, T=1100)
    dj, pj = jnp.asarray(data), jnp.asarray(p)
    want = float(sd.get_loss(spec, pj, dj))
    got2 = float(score_scan.get_loss(spec, pj, dj))
    np.testing.assert_allclose(got2, want, rtol=1e-8)
    got3 = float(score_scan.get_loss(spec, pj, dj, sweeps=3))
    assert abs(got3 - want) < abs(got2 - want) or got2 == want
    np.testing.assert_allclose(got3, want, rtol=1e-10)


def test_score_tree_sweep_convergence_monotone(rng):
    """The K-sweep gap to the sequential scan shrinks monotonically, and by
    about the chunk's own ≈B^L forgetting per sweep (0.97³² ≈ 0.38 here, so
    three extra sweeps buy an order of magnitude).  A is inflated ×50 so the
    γ path genuinely wanders from ω — at the stable point the pass-A
    surrogate is so accurate the K=1 gap is already float noise and there is
    nothing left to contract."""
    spec, p, data = _msed_case(rng, T=1100)
    p = p.copy()
    p[0] *= 50.0
    dj, pj = jnp.asarray(data), jnp.asarray(p)
    want = float(sd.get_loss(spec, pj, dj))
    gaps = [abs(float(score_scan.get_loss(spec, pj, dj, sweeps=k, chunk=32))
                - want)
            for k in (1, 2, 3, 4)]
    assert all(g1 > g2 for g1, g2 in zip(gaps, gaps[1:])), gaps
    assert gaps[-1] < 0.1 * gaps[0]


def test_score_tree_random_walk_family(rng):
    """The RWSD lineage (B absorbed — γ is a pure random walk, the affine
    elements have J = I off-observation): sequential parity at the fixed
    point and oracle parity at matched (sweeps, chunk)."""
    spec, p, data = _msed_case(rng, T=160, code="RWSD-NS")
    dj, pj = jnp.asarray(data), jnp.asarray(p)
    seq = float(sd.get_loss(spec, pj, dj))
    tr = float(score_scan.get_loss(spec, pj, dj, sweeps=2, chunk=32))
    np.testing.assert_allclose(tr, seq, rtol=1e-6)
    preds_o, _, _ = oracle.linearized_score_filter(
        _struct(spec, p), np.asarray(MATS), data, sweeps=2, chunk=32)
    want = oracle.msed_loss_from_preds(preds_o, data)
    np.testing.assert_allclose(tr, want, rtol=1e-12)


def test_score_tree_nan_semantics(rng):
    """Window/NaN contract shared with the sequential engine: an in-window
    NaN target poisons the loss to the −Inf sentinel on BOTH engines (the
    reference masks via start/end windows, not NaN skipping); excluding the
    block by window restores finite parity; a partially-quoted observed
    column poisons the state (code carries the cause)."""
    spec, p, data = _msed_case(rng, T=160)
    pj = jnp.asarray(p)
    pan = data.copy()
    pan[:, 40:44] = np.nan
    seq = float(sd.get_loss(spec, pj, jnp.asarray(pan)))
    tr = float(score_scan.get_loss(spec, pj, jnp.asarray(pan), sweeps=2,
                                   chunk=32))
    assert seq == -np.inf and tr == -np.inf
    seq_w = float(sd.get_loss(spec, pj, jnp.asarray(pan), start=45, end=160))
    tr_w = float(score_scan.get_loss(spec, pj, jnp.asarray(pan), start=45,
                                     end=160, sweeps=2, chunk=32))
    np.testing.assert_allclose(tr_w, seq_w, rtol=1e-8)
    poi = data.copy()
    poi[3, 50] = np.nan                  # partial: y[0] still finite
    ll, code = score_scan.get_loss_coded(spec, pj, jnp.asarray(poi))
    assert float(ll) == -np.inf
    assert "STATE_EXPLODED" in tax.decode(int(code))


# ---------------------------------------------------------------------------
# grad parity + trace counters
# ---------------------------------------------------------------------------

def test_score_tree_grad_parity_vs_sequential(rng):
    """Differentiable end-to-end INCLUDING the tree (the deliberate
    no-stop_gradient divergence from the SLR engine — the state is tiny and
    B^L forgetting is weak at B → 1, so the full adjoint is both cheap and
    needed): K=2 gradient against the sequential scan's, K=3 tightening
    it."""
    spec, p, data = _msed_case(rng, T=500)
    dj, pj = jnp.asarray(data), jnp.asarray(p)
    g_seq = np.asarray(jax.grad(lambda q: sd.get_loss(spec, q, dj))(pj))
    g2 = np.asarray(jax.grad(
        lambda q: score_scan.get_loss(spec, q, dj))(pj))
    g3 = np.asarray(jax.grad(
        lambda q: score_scan.get_loss(spec, q, dj, sweeps=3))(pj))
    assert np.isfinite(g2).all()
    scale = np.abs(g_seq).max()
    assert np.abs(g2 - g_seq).max() / scale < 1e-8
    assert np.abs(g3 - g_seq).max() / scale < 1e-10


def test_score_tree_no_recompile_trace_counter(rng):
    """Same-shape repeat calls reuse ONE traced program; a different static
    configuration (sweeps) traces its own."""
    spec, p, data = _msed_case(rng, T=96)
    dj, pj = jnp.asarray(data), jnp.asarray(p)
    fn = jax.jit(lambda q, d: score_scan.get_loss(spec, q, d))
    score_scan.reset_trace_counts()
    fn(pj, dj).block_until_ready()
    fn(pj * 1.001, dj).block_until_ready()
    fn(pj * 0.999, dj).block_until_ready()
    assert score_scan.trace_counts["score_filter"] == 1
    fn3 = jax.jit(lambda q, d: score_scan.get_loss(spec, q, d, sweeps=3))
    fn3(pj, dj).block_until_ready()
    assert score_scan.trace_counts["score_filter"] == 2


def test_score_tree_validation_errors(rng):
    spec, p, data = _msed_case(rng, T=40)
    dj, pj = jnp.asarray(data), jnp.asarray(p)
    with pytest.raises(ValueError, match="sweeps"):
        score_scan.get_loss(spec, pj, dj, sweeps=0)
    with pytest.raises(ValueError, match="prefix"):
        score_scan.get_loss(spec, pj, dj, prefix="zigzag")
    with pytest.raises(ValueError, match="chunk"):
        score_scan.get_loss(spec, pj, dj, chunk=0)
    ssd, _ = yfm.create_model("SSD-NS", MATS, float_type="float64")
    with pytest.raises(ValueError, match="supports_score_tree"):
        score_scan.get_loss(ssd, jnp.zeros(ssd.n_params), dj)


# ---------------------------------------------------------------------------
# ladder: score_tree as the MSED long-panel rescue rung
# ---------------------------------------------------------------------------

def test_ladder_score_tree_rung_rescues_long_panel(rng, monkeypatch):
    """A start the scan-engine diagnosis declares dead on a long panel
    (T >= ASSOC_RESCUE_MIN_T) is re-evaluated on the score-tree rung — the
    MSED twin of the assoc/slr rungs — and the trace says so.  The dead
    diagnosis is injected (tax.diagnose stubbed to −Inf) so the rung's
    gating and recovery wiring are pinned deterministically, independent of
    hunting for a point where only the fused sequential artifact dies."""
    spec, p, data = _msed_case(rng, T=ladder.ASSOC_RESCUE_MIN_T + 40)
    raw = np.asarray(untransform_params(spec, jnp.asarray(p)))
    monkeypatch.setattr(tax, "diagnose",
                        lambda *a, **k: (float("-inf"), 0))
    tr = ladder.escalate(spec, data, raw)
    assert [r.rung for r in tr.rungs] == ["scan", "score_tree"]
    assert tr.recovered and tr.rung == "score_tree"
    assert tr.engine == "score_tree" and tr.raw is None
    want = float(score_scan.get_loss(spec, jnp.asarray(p),
                                     jnp.asarray(data)))
    np.testing.assert_allclose(tr.ll, want, rtol=1e-12)


def test_ladder_score_tree_rung_skipped_on_short_panels(rng, monkeypatch):
    """Below the length gate the rung must not run (the sequential rungs are
    cheap there); an MSED spec has no sqrt/jitter rungs, so a still-dead
    start falls through to the reference-parity shrink."""
    spec, p, data = _msed_case(rng, T=60)
    raw = np.asarray(untransform_params(spec, jnp.asarray(p)))
    monkeypatch.setattr(tax, "diagnose",
                        lambda *a, **k: (float("-inf"), 0))
    tr = ladder.escalate(spec, data, raw)
    assert "score_tree" not in [r.rung for r in tr.rungs]
    assert [r.rung for r in tr.rungs] == ["scan", "shrink"]
    assert not tr.recovered


# ---------------------------------------------------------------------------
# estimation: the time-sharded objective's shard-aligned chunk
# ---------------------------------------------------------------------------

def test_time_sharded_loss_msed_matches_unsharded_engine(rng):
    """The sharded program equals the UNSHARDED score-tree engine at the
    same (chunk, sweeps) bit-tight — the refinement's (C, L) reshape IS the
    sharding layout (the same shard-aligned-chunk pin the SLR engine
    carries; a misaligned chunk was observed to MISCOMPILE under SPMD)."""
    from yieldfactormodels_jl_tpu.parallel.mesh import make_mesh
    from yieldfactormodels_jl_tpu.parallel.time_parallel import (
        _pad_time, get_loss_time_sharded)

    spec, p, data = _msed_case(rng, T=250)   # 250 % 8 != 0: ragged T works
    mesh = make_mesh(axis_name="time")
    n_dev = int(mesh.devices.size)
    par = float(get_loss_time_sharded(spec, p, data, mesh=mesh))
    padded = np.asarray(_pad_time(jnp.asarray(data), n_dev))
    chunk = padded.shape[1] // n_dev
    want = float(score_scan.get_loss(spec, jnp.asarray(p),
                                     jnp.asarray(padded), 0, data.shape[1],
                                     prefix="interleaved", chunk=chunk))
    np.testing.assert_allclose(par, want, rtol=1e-12)


# ---------------------------------------------------------------------------
# serving: refilter() stays a moment-engine surface
# ---------------------------------------------------------------------------

def test_refilter_rejects_momentless_tree_engines():
    """The serving refilter needs filtered MOMENTS (mean + covariance) —
    the score tree emits states only, so the builder's explicit dispatch
    must refuse an MSED spec loudly instead of silently falling back."""
    from yieldfactormodels_jl_tpu.serving.online import _jitted_refilter

    spec, _ = yfm.create_model("SD-NS", MATS, float_type="float64")
    with pytest.raises(ValueError, match="refilter"):
        _jitted_refilter(spec, 64)
