"""Kalman engine selection: all four engines agree through the public API."""

import numpy as np
import jax.numpy as jnp
import pytest

import yieldfactormodels_jl_tpu as yfm
from yieldfactormodels_jl_tpu.models import api

MATS = tuple(np.array([3, 12, 24, 60, 120, 240, 360]) / 12.0)


@pytest.fixture
def dns_case(rng):
    spec, _ = yfm.create_model("1C", MATS, float_type="float64")
    p = np.zeros(spec.n_params)
    p[0] = np.log(0.45)
    p[1] = 4e-4
    k = 2
    for j in range(3):
        for i in range(j + 1):
            p[k] = 0.05 if i == j else 0.004
            k += 1
    p[8:11] = [0.1, -0.05, 0.02]
    p[11:20] = (0.92 * np.eye(3)).reshape(-1)
    data = 0.4 * rng.standard_normal((len(MATS), 50)) + 4.0
    return spec, jnp.asarray(p), jnp.asarray(data)


def test_all_engines_agree(dns_case):
    spec, p, data = dns_case
    vals = {e: float(api.get_loss(spec, p, data, 1, 48, engine=e))
            for e in yfm.KALMAN_ENGINES}
    base = vals["univariate"]
    assert np.isfinite(base)
    for e, v in vals.items():
        np.testing.assert_allclose(v, base, rtol=1e-7, err_msg=e)


@pytest.mark.parametrize("code", ["AFNS5", "TVλ"])
def test_all_engines_agree_other_families(code, rng):
    """Engine agreement beyond DNS3: the AFNS intercept and the TVλ EKF's
    state-dependent rows must produce the same loglik through every engine
    the family supports — config.engines_for(spec), the introspection seam
    api.get_loss itself dispatches on (TVλ lists 'slr' instead of 'assoc';
    at T=50 the panel fits one SLR chunk, so the iterated engine is the
    sequential EKF to float rounding)."""
    from tests.oracle import generic_stable_params

    spec, _ = yfm.create_model(code, MATS, float_type="float64")
    p = jnp.asarray(generic_stable_params(spec, rng))
    data = jnp.asarray(0.4 * rng.standard_normal((len(MATS), 50)) + 4.0)
    engines = yfm.engines_for(spec)
    assert len(engines) >= 4
    vals = {e: float(api.get_loss(spec, p, data, 1, 48, engine=e))
            for e in engines}
    base = vals["univariate"]
    assert np.isfinite(base), f"{code}: non-finite base loglik"
    for e, v in vals.items():
        np.testing.assert_allclose(v, base, rtol=1e-7, err_msg=f"{code}:{e}")


def test_process_wide_engine_setting(dns_case):
    spec, p, data = dns_case
    base = float(api.get_loss(spec, p, data))
    try:
        yfm.set_kalman_engine("sqrt")
        assert yfm.kalman_engine() == "sqrt"
        np.testing.assert_allclose(float(api.get_loss(spec, p, data)), base,
                                   rtol=1e-7)
    finally:
        yfm.set_kalman_engine("univariate")
    with pytest.raises(ValueError):
        yfm.set_kalman_engine("bogus")
    with pytest.raises(ValueError):
        api.get_loss(spec, p, data, engine="Sqrt")  # per-call typo must raise


def test_engine_switch_clears_jitted_estimation_caches(dns_case):
    """set_kalman_engine must invalidate the lru-cached jitted losses in the
    estimation layer, or a process-wide switch silently keeps running the old
    traced engine."""
    spec, p, data = dns_case
    from yieldfactormodels_jl_tpu.estimation import optimize

    from yieldfactormodels_jl_tpu.estimation import bootstrap
    from yieldfactormodels_jl_tpu.parallel import mesh  # noqa: F401 -- registers its caches

    optimize._jitted_loss(spec, data.shape[1])       # populate lru caches
    bootstrap._jitted_grid_loss(spec, data.shape[1])
    assert optimize._jitted_loss.cache_info().currsize >= 1
    assert bootstrap._jitted_grid_loss.cache_info().currsize >= 1
    try:
        yfm.set_kalman_engine("sqrt")
        assert optimize._jitted_loss.cache_info().currsize == 0
        assert bootstrap._jitted_grid_loss.cache_info().currsize == 0
    finally:
        yfm.set_kalman_engine("univariate")


def test_sqrt_engine_neg_inf_on_invalid_factorization(dns_case, rng):
    """Non-stationary Φ ⇒ indefinite P0 ⇒ −Inf sentinel (not a silently
    altered prior)."""
    spec, p, data = dns_case
    bad = np.asarray(p).copy()
    lo, hi = spec.layout["phi"]
    bad[lo:hi] = (1.05 * np.eye(spec.state_dim)).reshape(-1)  # explosive
    v = float(api.get_loss(spec, jnp.asarray(bad), data, engine="sqrt"))
    assert v == -np.inf


def test_engines_for_validation_tvl(rng):
    """The blunt family gating is gone: an EXPLICIT engine the family does
    not support raises naming config.engines_for(spec); a process-wide
    default that does not apply silently falls back to the sequential
    default (a call that chose nothing must not error)."""
    spec, _ = yfm.create_model("TVλ", MATS, float_type="float64")
    p = np.zeros(spec.n_params)
    p[0] = 4e-4
    k = 1
    for j in range(4):
        for i in range(j + 1):
            p[k] = 0.05 if i == j else 0.002
            k += 1
    p[11:15] = [0.1, -0.05, 0.02, np.log(0.45)]
    p[15:31] = (0.9 * np.eye(4)).reshape(-1)
    data = 0.4 * rng.standard_normal((len(MATS), 30)) + 4.0
    with pytest.raises(ValueError, match="engines_for") as ei:
        api.get_loss(spec, jnp.asarray(p), jnp.asarray(data), engine="assoc")
    assert "'slr'" in str(ei.value)          # the message lists the valid set
    u = float(api.get_loss(spec, jnp.asarray(p), jnp.asarray(data),
                           engine="univariate"))
    try:
        yfm.set_kalman_engine("assoc")
        v = float(api.get_loss(spec, jnp.asarray(p), jnp.asarray(data)))
    finally:
        yfm.set_kalman_engine("univariate")
    np.testing.assert_allclose(v, u, rtol=1e-12)
