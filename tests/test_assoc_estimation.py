"""Assoc-scan estimation engine (docs/DESIGN.md §13) acceptance tests.

Oracle-backed parity of EVERY Kalman engine (the canonical coverage the
test_conventions.py engine-guard pins), long-T assoc + time-sharded parity at
T=2048 with NaN gaps and window masks, differentiable-assoc grad parity
against the scan engine, the ``YFM_LOGLIK_T_SWITCH`` dispatch policy, the
multi-start cascade end-to-end on the assoc engine, the escalation ladder's
assoc rescue rung, the structured per-step-contribution errors, and the
serving ``refilter()`` drift regression against 5,000 accumulated O(1)
updates.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import yieldfactormodels_jl_tpu as yfm
from tests import oracle
from yieldfactormodels_jl_tpu import config
from yieldfactormodels_jl_tpu.models import api
from yieldfactormodels_jl_tpu.models.params import untransform_params
from yieldfactormodels_jl_tpu.ops import assoc_scan, univariate_kf
from yieldfactormodels_jl_tpu.robustness import ladder, taxonomy as tax

MATS = tuple(np.array([3, 12, 24, 60, 120, 240, 360]) / 12.0)

#: literal twin of config.KALMAN_ENGINES — literal ON PURPOSE: the
#: engine-coverage guard in test_conventions.py greps test ASTs for these
#: names, and test_engine_list_is_in_sync below forces this list to track
#: the registry, so a new engine cannot ship without oracle parity here
ALL_ENGINES = ("univariate", "sqrt", "joint", "assoc", "slr")


def _case(rng, T=120, dtype=np.float64):
    spec, _ = yfm.create_model("1C", MATS, float_type="float64")
    p = oracle.stable_1c_params(spec, dtype)
    data = 0.4 * rng.standard_normal((len(MATS), T)) + 4.0
    return spec, p, data


def _oracle_pieces(spec, p):
    Z = oracle.dns_loadings(float(p[spec.layout["gamma"][0]]),
                            np.asarray(MATS))
    Ms = spec.state_dim
    C = np.zeros((Ms, Ms))
    rows, cols = spec.chol_indices
    a, _ = spec.layout["chol"]
    for k, (r, c) in enumerate(zip(rows, cols)):
        C[r, c] = p[a + k]
    lo, hi = spec.layout["delta"]
    delta = np.asarray(p[lo:hi], dtype=np.float64)
    lo, hi = spec.layout["phi"]
    Phi = np.asarray(p[lo:hi], dtype=np.float64).reshape(Ms, Ms)
    return Z, Phi, delta, C @ C.T, float(p[spec.layout["obs_var"][0]])


def test_engine_list_is_in_sync():
    """The literal ALL_ENGINES list must track config.KALMAN_ENGINES — a new
    engine breaks this first, forcing its oracle parity row below."""
    assert ALL_ENGINES == tuple(yfm.KALMAN_ENGINES)


@pytest.mark.parametrize("engine",
                         ["univariate", "sqrt", "joint", "assoc", "slr"])
def test_engine_oracle_parity_with_nan_gap(engine, rng):
    """Every loglik engine vs the independent NumPy float64 loop
    (tests/oracle.py), interior NaN gap included — oracle-backed, never
    JAX-vs-JAX alone (CLAUDE.md)."""
    spec, p, data = _case(rng)
    data[:, 40:44] = np.nan
    Z, Phi, delta, Om, ov = _oracle_pieces(spec, p)
    want = oracle.kalman_filter_loglik(Z, Phi, delta, Om, ov, data)
    got = float(api.get_loss(spec, jnp.asarray(p), jnp.asarray(data),
                             engine=engine))
    np.testing.assert_allclose(got, want, rtol=1e-8, err_msg=engine)


@pytest.mark.slow
def test_assoc_long_t_oracle_parity_sharded(rng):
    """T=2048 on the 8 virtual devices: assoc + time-sharded loss vs the
    sequential NumPy oracle, with a NaN-gap window and start/end masking
    (masking == truncation, so the window maps onto the oracle's panel)."""
    from yieldfactormodels_jl_tpu.parallel.mesh import make_mesh
    from yieldfactormodels_jl_tpu.parallel.time_parallel import (
        get_loss_time_sharded)

    T, s, e = 2048, 4, 2040
    spec, p, data = _case(rng, T=T)
    data[:, 700:708] = np.nan          # interior NaN gap inside the window
    Z, Phi, delta, Om, ov = _oracle_pieces(spec, p)
    want = oracle.kalman_filter_loglik(Z, Phi, delta, Om, ov, data[:, s:e])
    got_assoc = float(assoc_scan.get_loss(spec, jnp.asarray(p),
                                          jnp.asarray(data), s, e))
    np.testing.assert_allclose(got_assoc, want, rtol=1e-8)
    mesh = make_mesh(axis_name="time")
    assert mesh.devices.size == 8
    got_sharded = float(get_loss_time_sharded(spec, p, data, start=s, end=e,
                                              mesh=mesh))
    np.testing.assert_allclose(got_sharded, want, rtol=1e-8)


def test_time_sharded_loss_ragged_length(rng):
    """T not divisible by the mesh: the panel is NaN-padded to a device
    multiple with ``end`` at the true length — exact, not approximate
    (real daily histories have arbitrary length)."""
    from yieldfactormodels_jl_tpu.parallel.mesh import make_mesh
    from yieldfactormodels_jl_tpu.parallel.time_parallel import (
        get_loss_time_sharded)

    spec, p, data = _case(rng, T=250)       # 250 % 8 != 0
    seq = float(univariate_kf.get_loss(spec, jnp.asarray(p),
                                       jnp.asarray(data)))
    par = float(get_loss_time_sharded(spec, p, data,
                                      mesh=make_mesh(axis_name="time")))
    np.testing.assert_allclose(par, seq, rtol=1e-9)


def test_assoc_grad_parity_vs_scan_engine(rng):
    """The differentiable assoc loss: gradient vs the scan engine's at the
    stable point, T=360 (the acceptance panel length)."""
    spec, p, data = _case(rng, T=360)
    data[:, 100:104] = np.nan
    dj, pj = jnp.asarray(data), jnp.asarray(p)
    g_assoc = np.asarray(jax.grad(
        lambda q: assoc_scan.get_loss(spec, q, dj))(pj))
    g_scan = np.asarray(jax.grad(
        lambda q: univariate_kf.get_loss(spec, q, dj))(pj))
    assert np.isfinite(g_assoc).all()
    np.testing.assert_allclose(
        np.linalg.norm(g_assoc - g_scan) / np.linalg.norm(g_scan), 0.0,
        atol=1e-10)


def test_assoc_taxonomy_codes(rng):
    """Assoc-engine non-finite losses carry decoded causes like every other
    engine (robustness/taxonomy.py channel)."""
    spec, p, data = _case(rng)
    dj = jnp.asarray(data)
    ll, code = assoc_scan.get_loss_coded(spec, jnp.asarray(p), dj)
    assert np.isfinite(float(ll)) and int(code) == tax.OK
    bad = p.copy()
    bad[spec.layout["obs_var"][0]] = -10.0
    ll, code = assoc_scan.get_loss_coded(spec, jnp.asarray(bad), dj)
    assert float(ll) == -np.inf and tax.decode(code)  # a named cause, not 0
    nanp = p.copy()
    nanp[0] = np.nan
    _, code = assoc_scan.get_loss_coded(spec, jnp.asarray(nanp), dj)
    assert "TRANSFORM_OVERFLOW" in tax.decode(code)
    _, code = assoc_scan.get_loss_coded(spec, jnp.asarray(p), dj, 5, 6)
    assert "MISSING_ALL_OBS" in tax.decode(code)


def test_assoc_stabilized_mode_matches_at_stable_point(rng):
    """psd_floor (the sqrt-stabilized recovery surface) is a no-op at a
    healthy point — projection only clips what was already indefinite."""
    spec, p, data = _case(rng)
    a = float(assoc_scan.get_loss(spec, jnp.asarray(p), jnp.asarray(data)))
    s = float(assoc_scan.get_loss(spec, jnp.asarray(p), jnp.asarray(data),
                                  psd_floor=ladder.SQRT_RESCUE_FLOOR))
    np.testing.assert_allclose(s, a, rtol=1e-9)


# ---------------------------------------------------------------------------
# engine-dispatch policy (YFM_LOGLIK_T_SWITCH)
# ---------------------------------------------------------------------------

def test_t_switch_dispatches_long_panels_to_assoc(rng, monkeypatch):
    spec, p, data = _case(rng, T=100)
    dj, pj = jnp.asarray(data), jnp.asarray(p)
    calls = []
    real = assoc_scan.get_loss
    monkeypatch.setattr(assoc_scan, "get_loss",
                        lambda *a, **k: calls.append(1) or real(*a, **k))
    try:
        config.set_loglik_t_switch(64)
        api.get_loss(spec, pj, dj)                 # T=100 >= 64 → assoc
        assert len(calls) == 1
        api.get_loss(spec, pj, dj[:, :50])         # T=50 < 64 → sequential
        assert len(calls) == 1
        api.get_loss(spec, pj, dj, engine="univariate")  # explicit wins
        assert len(calls) == 1
        config.set_loglik_t_switch(0)
        api.get_loss(spec, pj, dj)                 # policy off
        assert len(calls) == 1
    finally:
        config.set_loglik_t_switch(0)


def test_t_switch_env_resolution_and_validation(monkeypatch):
    monkeypatch.setenv("YFM_LOGLIK_T_SWITCH", "4096")
    monkeypatch.setattr(config, "_LOGLIK_T_SWITCH", None)  # force re-resolve
    assert config.loglik_t_switch() == 4096
    config.set_loglik_t_switch(0)
    with pytest.raises(ValueError):
        config.set_loglik_t_switch(-1)


def test_t_switch_clears_jitted_estimation_caches(rng):
    """set_loglik_t_switch must invalidate the registered engine caches —
    the dispatch is read at trace time (same contract as
    set_kalman_engine)."""
    from yieldfactormodels_jl_tpu.estimation import optimize

    spec, p, data = _case(rng, T=50)
    optimize._jitted_loss(spec, 50)
    assert optimize._jitted_loss.cache_info().currsize >= 1
    try:
        config.set_loglik_t_switch(16)
        assert optimize._jitted_loss.cache_info().currsize == 0
    finally:
        config.set_loglik_t_switch(0)


# ---------------------------------------------------------------------------
# the multi-start cascade on the assoc engine
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_estimate_cascade_on_assoc_engine(rng):
    """estimate() end-to-end with the assoc engine selected via the T-switch,
    vs the scan-engine cascade — parameter estimates within optimizer
    tolerance (the engines agree to float64 rounding, so the optimizer
    trajectories stay together)."""
    from yieldfactormodels_jl_tpu.estimation import optimize

    spec, p, data = _case(rng, T=80)
    starts = np.stack([p, p * 1.02], axis=1)
    base = optimize.estimate(spec, data, starts, max_iters=40)
    try:
        config.set_loglik_t_switch(1)          # every panel rides the tree
        ts = optimize.estimate(spec, data, starts, max_iters=40)
    finally:
        config.set_loglik_t_switch(0)
    assert np.isfinite(base[1]) and np.isfinite(ts[1])
    np.testing.assert_allclose(ts[1], base[1], rtol=1e-6)
    np.testing.assert_allclose(ts[2], base[2], rtol=1e-3, atol=1e-4)


@pytest.mark.slow
def test_estimate_steps_on_assoc_engine(rng):
    """The block-coordinate cascade with the process engine forced to assoc
    — same contract as the scan run within ΔLL tolerance."""
    from yieldfactormodels_jl_tpu.estimation import optimize

    spec, p, data = _case(rng, T=60)
    groups = spec.default_param_groups()
    base = optimize.estimate_steps(spec, data, p[:, None], groups,
                                   max_group_iters=2)
    yfm.set_kalman_engine("assoc")
    try:
        got = optimize.estimate_steps(spec, data, p[:, None], groups,
                                      max_group_iters=2)
    finally:
        yfm.set_kalman_engine("univariate")
    np.testing.assert_allclose(got[1], base[1], rtol=1e-6)


@pytest.mark.slow
def test_estimate_time_sharded_objective(rng):
    """estimate(objective="time_sharded"): the assoc loss over the sharded
    time axis drives the same multi-start L-BFGS artifact."""
    from yieldfactormodels_jl_tpu.estimation import optimize

    spec, p, data = _case(rng, T=250)       # 250 % 8 != 0: ragged T works
    starts = np.stack([p, p * 0.99], axis=1)
    base = optimize.estimate(spec, data, starts, max_iters=15,
                             objective="vmap")
    ts = optimize.estimate(spec, data, starts, max_iters=15,
                           objective="time_sharded")
    np.testing.assert_allclose(ts[1], base[1], rtol=1e-6)
    # TVλ is covered now (the iterated-SLR engine — tests/test_slr_scan.py);
    # a family with NO parallel-in-time engine still gets the structured
    # error, via the config.engines_for introspection seam
    with pytest.raises(ValueError, match="time_sharded"):
        ns_spec, _ = yfm.create_model("NS", MATS, float_type="float64")
        optimize.estimate(ns_spec, data, np.zeros((ns_spec.n_params, 1)),
                          objective="time_sharded")


# ---------------------------------------------------------------------------
# ladder: assoc as a long-panel rescue rung
# ---------------------------------------------------------------------------

def _nonpsd_start(spec, p):
    bad = np.asarray(p, dtype=np.float64).copy()
    a, b = spec.layout["phi"]
    Phi = 0.9 * np.eye(3)
    Phi[0, 1] = Phi[1, 0] = Phi[0, 2] = Phi[2, 0] = 0.8
    Phi[1, 2] = Phi[2, 1] = 0.8
    bad[a:b] = Phi.reshape(-1)
    return bad


@pytest.mark.slow
def test_ladder_assoc_rung_rescues_long_panel(rng):
    """A dead start on a long panel (T >= ASSOC_RESCUE_MIN_T) is recovered
    by the assoc rung — O(log T) span instead of another sequential walk —
    and the trace says so."""
    spec, p, data = _case(rng, T=ladder.ASSOC_RESCUE_MIN_T + 76)
    raw_bad = np.asarray(untransform_params(
        spec, jnp.asarray(_nonpsd_start(spec, p))))
    tr = ladder.escalate(spec, data, raw_bad)
    assert [r.rung for r in tr.rungs] == ["scan", "assoc"]
    assert tr.recovered and tr.rung == "assoc" and tr.engine == "assoc"
    assert np.isfinite(tr.ll)


def test_ladder_assoc_rung_skipped_on_short_panels(rng):
    """Below the length gate the ladder keeps its historical scan → sqrt
    climb (the existing sqrt-rung tests pin the exact rung lists)."""
    spec, p, data = _case(rng, T=60)
    raw_bad = np.asarray(untransform_params(
        spec, jnp.asarray(_nonpsd_start(spec, p))))
    tr = ladder.escalate(spec, data, raw_bad)
    assert "assoc" not in [r.rung for r in tr.rungs]
    assert tr.recovered and tr.rung == "sqrt"


# ---------------------------------------------------------------------------
# inference: structured per-step-contribution errors
# ---------------------------------------------------------------------------

def test_per_step_contributions_error_is_structured(rng):
    from yieldfactormodels_jl_tpu.estimation.inference import (
        PerStepContributionsUnavailable, _jitted_score_contributions,
        mle_standard_errors)

    spec, p, data = _case(rng, T=40)
    for eng in ("sqrt", "assoc"):
        with pytest.raises(PerStepContributionsUnavailable,
                           match="'joint' and 'univariate'") as ei:
            mle_standard_errors(spec, p, data, kind="sandwich", engine=eng)
        assert ei.value.engine == eng
        assert ei.value.supported == ("joint", "univariate")
        # the guard lives at the builder too — every caller hits it
        with pytest.raises(PerStepContributionsUnavailable):
            _jitted_score_contributions(spec, 40, eng)
    # and it is a ValueError, so generic validation handlers still catch it
    assert issubclass(PerStepContributionsUnavailable, ValueError)


# ---------------------------------------------------------------------------
# serving: refilter() — exact rebuild vs 5k accumulated O(1) updates
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_refilter_agrees_with_accumulated_updates(rng):
    """Drift regression (acceptance): a clean 5,000-update run, PSD at every
    checkpoint, then one O(log T) refilter whose rebuilt state matches the
    accumulated recursive state to float64 rounding."""
    from yieldfactormodels_jl_tpu.serving import (YieldCurveService,
                                                  freeze_snapshot)

    spec, _ = yfm.create_model("1C", MATS, float_type="float64")
    p = oracle.stable_1c_params(spec, np.float64)
    T_cond, n_upd = 64, 5000
    panel = oracle.simulate_dns_panel(rng, np.asarray(MATS),
                                      T=T_cond + n_upd)
    svc = YieldCurveService(freeze_snapshot(spec, p, panel[:, :T_cond]))
    i = T_cond
    while i < T_cond + n_upd:
        j = min(i + 128, T_cond + n_upd)
        lls = svc.update_many(j, panel[:, i:j])
        assert np.isfinite(lls).all()
        w = np.linalg.eigvalsh(np.asarray(svc.snapshot.P))
        assert w.min() > 0, f"covariance left the PSD cone at update {i}"
        i = j
    assert svc.version == n_upd
    beta_acc = np.asarray(svc.snapshot.beta).copy()
    P_acc = np.asarray(svc.snapshot.P).copy()
    ll = svc.refilter(panel, date="rebuild")
    assert np.isfinite(ll)
    assert svc.version == n_upd + 1 and not svc.stale
    np.testing.assert_allclose(np.asarray(svc.snapshot.beta), beta_acc,
                               atol=1e-10)
    np.testing.assert_allclose(np.asarray(svc.snapshot.P), P_acc, atol=1e-10)
    assert np.linalg.eigvalsh(np.asarray(svc.snapshot.P)).min() > 0
    # the rebuild is the strongest refresh: cadence reset, state last-good
    assert svc._updates_since_refresh == 0
    np.testing.assert_array_equal(np.asarray(svc.last_good_snapshot.beta),
                                  np.asarray(svc.snapshot.beta))


def test_refilter_sqrt_engine_and_validation(rng):
    from yieldfactormodels_jl_tpu.serving import (ServingError,
                                                  YieldCurveService,
                                                  freeze_snapshot)

    spec, _ = yfm.create_model("1C", MATS, float_type="float64")
    p = oracle.stable_1c_params(spec, np.float64)
    panel = oracle.simulate_dns_panel(rng, np.asarray(MATS), T=96)
    svc = YieldCurveService(freeze_snapshot(spec, p, panel[:, :64]),
                            engine="sqrt")
    for t in range(64, 96):
        svc.update(t, panel[:, t])
    ll = svc.refilter(panel)
    assert np.isfinite(ll)
    S = np.asarray(svc._state.cov)          # sqrt engine: factor, P = S Sᵀ
    np.testing.assert_allclose(S @ S.T, np.asarray(svc.snapshot.P),
                               atol=1e-10)
    with pytest.raises(ServingError, match="refilter"):
        svc.refilter(panel[:2])             # wrong shape
    tvl_spec, _ = yfm.create_model("TVλ", MATS, float_type="float64")
    tvl_p = oracle.stable_tvl_params(tvl_spec)
    tvl_svc = YieldCurveService(
        freeze_snapshot(tvl_spec, tvl_p, panel[:, :64]))
    # TVλ snapshots re-filter on the iterated-SLR engine now (docs/DESIGN.md
    # §19; the accumulated-updates drift regression lives in
    # tests/test_slr_scan.py)
    assert np.isfinite(tvl_svc.refilter(panel))
