"""Multi-device sharding tests on the virtual 8-device CPU mesh (SURVEY.md §4)."""

import os

import jax
import jax.numpy as jnp
import numpy as np

from yieldfactormodels_jl_tpu import create_model, get_loss
from yieldfactormodels_jl_tpu.parallel import mesh as pmesh
from yieldfactormodels_jl_tpu.parallel.multihost import host_task_slice, sweep_stale_locks

MATS = tuple(np.array([3.0, 12.0, 24.0, 60.0, 120.0, 360.0]) / 12.0)


def _panel(T=40):
    rng = np.random.default_rng(5)
    return np.cumsum(rng.standard_normal((len(MATS), T)) * 0.1, axis=1) + 5.0


def _static_params(spec, n_batch, jitter=0.0):
    p = np.zeros(spec.n_params)
    p[0] = np.log(0.5)
    p[1:4] = [0.3, -0.1, 0.05]
    p[4:13] = np.diag([0.9, 0.85, 0.8]).T.reshape(-1)
    batch = np.tile(p, (n_batch, 1))
    if jitter:
        batch += np.random.default_rng(0).uniform(-jitter, jitter, batch.shape)
    return batch


def test_mesh_uses_all_devices():
    m = pmesh.make_mesh()
    assert m.devices.size == 8


def test_sharded_batch_loss_matches_serial():
    spec, _ = create_model("NS", MATS, float_type="float64")
    data = _panel()
    batch = _static_params(spec, 13, jitter=0.05)  # non-multiple of 8 → padding
    out = np.asarray(pmesh.batch_loss_sharded(spec, batch, data))
    assert out.shape == (13,)
    for i in (0, 5, 12):
        want = float(get_loss(spec, jnp.asarray(batch[i]), jnp.asarray(data)))
        np.testing.assert_allclose(out[i], want, rtol=1e-9)


def test_sharded_multistart_runs_and_improves():
    spec, _ = create_model("NS", MATS, float_type="float64")
    data = _panel()
    from yieldfactormodels_jl_tpu.models.params import untransform_params

    batch = _static_params(spec, 8, jitter=0.1)
    raw = np.stack([np.asarray(untransform_params(spec, jnp.asarray(b))) for b in batch])
    xs, lls = pmesh.multistart_sharded(spec, raw, data, max_iters=30)
    assert xs.shape == (8, 13) and lls.shape == (8,)
    base = np.asarray(pmesh.batch_loss_sharded(spec, batch, data))
    assert np.nanmax(np.asarray(lls)) >= np.nanmax(base) - 1e-9


def test_sharded_particle_filter_matches_serial():
    """Draw-axis sharding must reproduce the single-device PF logliks
    exactly (same keys ⇒ same resampling path per draw)."""
    from tests.oracle import stable_1c_params
    from yieldfactormodels_jl_tpu.ops.particle import particle_filter_loglik

    spec, _ = create_model("1C", MATS, float_type="float64")
    data = _panel(T=24)
    p = stable_1c_params(spec, dtype=np.float64)
    draws = np.tile(p, (5, 1))  # non-multiple of 8 → padding
    draws += np.random.default_rng(1).uniform(-0.01, 0.01, draws.shape)
    keys = np.asarray(jax.random.split(jax.random.PRNGKey(7), 5))
    out = np.asarray(pmesh.particle_filter_sharded(
        spec, draws, data, keys=keys, n_particles=16,
        sv_phi=0.5, sv_sigma=0.1))
    assert out.shape == (5,)
    for i in (0, 4):
        want = float(particle_filter_loglik(
            spec, jnp.asarray(draws[i]), jnp.asarray(data),
            jnp.asarray(keys[i]), n_particles=16, sv_phi=0.5, sv_sigma=0.1))
        np.testing.assert_allclose(out[i], want, rtol=1e-9)


def test_sharded_bootstrap_grid_matches_serial():
    """Resample-axis sharding must reproduce bootstrap_lambda_grid (same key
    ⇒ same indices), padded rows trimmed before the stats."""
    from yieldfactormodels_jl_tpu.estimation.bootstrap import bootstrap_lambda_grid

    spec, _ = create_model("NS", MATS, float_type="float64")
    data = _panel()
    p = _static_params(spec, 1)[0]
    grid = np.array([0.3, 0.6, 0.9])
    key = jax.random.PRNGKey(11)
    want = bootstrap_lambda_grid(spec, p, data, grid, n_resamples=13,
                                 block_len=6, key=key)
    got = pmesh.bootstrap_grid_sharded(spec, p, data, grid, n_resamples=13,
                                       block_len=6, key=key)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), rtol=1e-9)


def test_host_task_slice_partition():
    tasks = list(range(100, 120))
    parts = [host_task_slice(tasks, process_id=i, num_processes=3) for i in range(3)]
    merged = sorted(t for p in parts for t in p)
    assert merged == tasks
    for i, j in [(0, 1), (0, 2), (1, 2)]:
        assert not set(parts[i]) & set(parts[j])


def test_stale_lock_sweep(tmp_path):
    root = str(tmp_path / "locks")
    d = os.path.join(root, "expanding", "task_5.lock")
    os.makedirs(d)
    old = 1.0
    os.utime(d, (old, old))
    fresh = os.path.join(root, "expanding", "task_6.lock")
    os.makedirs(fresh)
    removed = sweep_stale_locks(root, ttl_seconds=3600)
    assert d in removed
    assert not os.path.isdir(d)
    assert os.path.isdir(fresh)
