"""Second-order engine tests (ops/newton.py, docs/DESIGN.md §17).

Parity chain for the HVP recursions, per the engine-parity convention
(graftlint YFM007 — both ``config.NEWTON_ENGINES`` entries, "fisher" and
"exact", are pinned here against tests/oracle.py):

- the "exact" recursion (grad-of-directional-derivative, reverse over the
  tangent scan) vs the independent finite-difference NumPy Hessian oracle
  (``oracle.fd_hessian``) AND vs ``jax.jvp``-of-grad — the OPPOSITE
  differentiation order, so agreement is a real check, not an identity;
- the "fisher" matrix vs its own HVP composition, plus the structural
  facts the trust-region solver relies on (symmetry, PSD);
- the cascade: ``estimate(..., second_order=...)`` matches/beats the
  first-order path on the seed configs, ``second_order=False`` reproduces
  it bit-for-bit, and dead starts keep their sentinels.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.oracle import (fd_hessian, simulate_dns_panel, stable_1c_params,
                          stable_ns_params)
from yieldfactormodels_jl_tpu import create_model
from yieldfactormodels_jl_tpu.estimation import optimize as opt
from yieldfactormodels_jl_tpu.estimation.scenario import refit_column
from yieldfactormodels_jl_tpu.models import api
from yieldfactormodels_jl_tpu.models.params import untransform_params
from yieldfactormodels_jl_tpu.ops import newton as NT
from yieldfactormodels_jl_tpu.robustness import taxonomy as tax

MATS = (3.0, 6.0, 12.0, 24.0, 36.0, 60.0, 84.0, 120.0, 240.0, 360.0)


def _mats():
    return tuple(m / 12.0 for m in MATS)


def _raw_point(spec, p):
    return jnp.asarray(
        opt._sanitize(np.asarray(untransform_params(spec, jnp.asarray(p)))),
        dtype=jnp.float64)


@pytest.fixture(scope="module")
def dns_setup():
    spec, _ = create_model("1C", _mats(), float_type="float64")
    p = stable_1c_params(spec, np.float64)
    data = np.asarray(
        api.simulate(spec, jnp.asarray(p), 60, jax.random.PRNGKey(3))["data"])
    return spec, p, jnp.asarray(data)


@pytest.fixture(scope="module")
def ns_setup():
    spec, _ = create_model("NS", _mats(), float_type="float64")
    p = stable_ns_params(spec, np.float64)
    rng = np.random.default_rng(7)
    data = jnp.asarray(simulate_dns_panel(rng, np.asarray(_mats()), T=50))
    return spec, p, data


# ---------------------------------------------------------------------------
# HVP parity: the "exact" recursion vs the FD oracle vs jvp-of-grad
# ---------------------------------------------------------------------------

def _exact_parity(spec, p, data):
    T = data.shape[1]
    x = _raw_point(spec, p)
    rng = np.random.default_rng(0)
    u = jnp.asarray(rng.standard_normal(x.shape[0]))

    h_rec = np.asarray(NT.exact_hvp(spec, x, u, data, 0, T))
    # the opposite differentiation order: forward over reverse
    h_jg = np.asarray(jax.jvp(
        jax.grad(lambda q: NT._nll(spec, q, data, 0, T)), (x,), (u,))[1])
    scale = max(1.0, np.max(np.abs(h_rec)))
    np.testing.assert_allclose(h_rec / scale, h_jg / scale, atol=1e-7)

    # independent NumPy float64 FD Hessian of the same objective (the probe
    # is jitted ONCE — hundreds of eager scan dispatches would otherwise
    # accumulate XLA:CPU programs, the conftest segfault class)
    probe = jax.jit(lambda q: NT._clamped_nll(spec, q, data, 0, T))
    fun = lambda q: float(probe(jnp.asarray(q, dtype=jnp.float64)))
    H_fd = fd_hessian(fun, np.asarray(x), eps=5e-5)
    h_fd = H_fd @ np.asarray(u)
    np.testing.assert_allclose(h_rec / scale, h_fd / scale, atol=5e-4)


def test_exact_hvp_parity_1c(dns_setup):
    _exact_parity(*dns_setup)


def test_exact_hvp_parity_ns(ns_setup):
    # the static NS family rides the family-generic "exact" recursion (the
    # fisher engine resolves to it — resolve_mode below)
    _exact_parity(*ns_setup)


def test_fisher_matrix_matches_hvp_composition_and_is_psd(dns_setup):
    spec, p, data = dns_setup
    T = data.shape[1]
    x = _raw_point(spec, p)
    P = x.shape[0]
    Hm = np.asarray(NT.fisher_matrix(spec, x, data, 0, T))
    # the matrix assembled from the linearize sweep must act exactly like
    # the jvp+vjp HVP composition (3 random directions keep this fast; the
    # two paths share no code past the innovation function)
    rng = np.random.default_rng(2)
    for _ in range(3):
        u = jnp.asarray(rng.standard_normal(P))
        hu = np.asarray(NT.fisher_hvp(spec, x, u, data, 0, T))
        scale = max(1.0, np.max(np.abs(hu)))
        np.testing.assert_allclose((Hm @ np.asarray(u)) / scale, hu / scale,
                                   atol=1e-9)
    np.testing.assert_allclose(Hm, Hm.T, rtol=1e-12)
    assert np.linalg.eigvalsh(Hm).min() > 0  # "fisher" is PSD by construction


def test_resolve_mode_downgrades_fisher_for_non_kalman(ns_setup):
    spec, _, _ = ns_setup
    assert NT.resolve_mode(spec, "fisher") == "exact"
    with pytest.raises(ValueError):
        NT.resolve_mode(spec, "nonsense")


# ---------------------------------------------------------------------------
# the cascade: Newton-vs-LBFGS final losses, bit-for-bit off switch
# ---------------------------------------------------------------------------

def _starts(p, n, scale=0.1, seed=0):
    rng = np.random.default_rng(seed)
    return np.stack([p * (1 + scale * rng.standard_normal(p.shape))
                     for _ in range(n)], axis=1)


@pytest.mark.slow
def test_newton_polish_matches_lbfgs_optimum_1c(dns_setup):
    spec, p, data = dns_setup
    starts = _starts(p, 2)
    _, ll_base, best_base, _ = opt.estimate(
        spec, data, starts, max_iters=800, second_order=False)
    _, ll_so, best_so, conv = opt.estimate(
        spec, data, starts, max_iters=800, second_order="fisher")
    rep = opt.last_multistart_report()
    # the polish must reach at least the first-order optimum (it is allowed
    # to beat a stalled L-BFGS — measured on the seed configs it does)
    assert ll_so >= ll_base - 1e-6
    assert any(ph == "newton" for ph in rep["phase"])
    assert rep["newton"] is not None and sum(rep["newton"]["iters"]) > 0
    assert len(rep["iters"]) == 2 and len(rep["converged"]) == 2


def test_second_order_false_is_bit_for_bit(dns_setup):
    spec, p, data = dns_setup
    starts = _starts(p, 2)
    r1 = opt.estimate(spec, data, starts, max_iters=40, second_order=False)
    r2 = opt.estimate(spec, data, starts, max_iters=40)  # env knob unset
    np.testing.assert_array_equal(r1[2], r2[2])
    assert r1[1] == r2[1]
    assert "newton" not in opt.last_multistart_report()


def test_yfm_newton_env_knob_arms_cascade(dns_setup, monkeypatch):
    spec, p, data = dns_setup
    starts = _starts(p, 2)
    monkeypatch.setenv("YFM_NEWTON", "fisher")
    opt.estimate(spec, data, starts, max_iters=100)
    rep = opt.last_multistart_report()
    assert rep["newton"] is not None
    # explicit False overrides the knob — the historical path
    opt.estimate(spec, data, starts, max_iters=100, second_order=False)
    assert "newton" not in opt.last_multistart_report()
    monkeypatch.setenv("YFM_NEWTON", "bogus")
    with pytest.raises(ValueError):
        opt.estimate(spec, data, starts, max_iters=10)


def test_dead_start_stays_on_first_order_path(dns_setup):
    """Sentinel discipline: a start whose loss is -Inf everywhere near it
    is frozen by the polish at entry (done, not converged) and keeps the
    first-order result — no NaN leaks into the report."""
    spec, p, data = dns_setup
    # heavy off-diagonal Φ (spectral radius > 1): the kron-solve P₀ is
    # indefinite and the filter dies — the tests/test_robustness dead-start
    # construction, which survives the raw-space sanitize round-trip
    bad = p.copy()
    a, b = spec.layout["phi"]
    Phi = 0.9 * np.eye(3)
    Phi[0, 1] = Phi[1, 0] = Phi[0, 2] = Phi[2, 0] = Phi[1, 2] = Phi[2, 1] = 0.8
    bad[a:b] = Phi.reshape(-1)
    starts = np.stack([p, bad], axis=1)
    _, ll, _, _ = opt.estimate(spec, data, starts, max_iters=60,
                               second_order="fisher")
    rep = opt.last_multistart_report()
    assert np.isfinite(ll)
    # dead row stayed on the penalty plateau (−penalty, the historical
    # first-order sentinel) — the polish froze it at entry
    assert rep["lls"][1] <= -opt._PENALTY_THRESH
    assert rep["newton"]["iters"][1] == 0          # polish never moved it
    assert rep["phase"][1] == "lbfgs"


def test_nonpsd_hessian_code_reaches_report(dns_setup):
    """The exact engine far from the optimum sees an indefinite Hessian;
    the damped fallback must both still descend and raise the
    NONPSD_HESSIAN taxonomy bit into the report counters."""
    spec, p, data = dns_setup
    starts = _starts(p, 2, scale=0.6, seed=5)
    opt.estimate(spec, data, starts, max_iters=90, second_order="exact")
    rep = opt.last_multistart_report()
    codes = rep["newton"]["code"]
    assert any(c & tax.NONPSD_HESSIAN for c in codes)
    assert tax.describe(tax.NONPSD_HESSIAN) == "NONPSD_HESSIAN"


@pytest.mark.slow
def test_estimate_steps_second_order_polish(ns_setup):
    """estimate_steps gains a joint full-vector polish after the
    block-coordinate cascade; accept-if-improved keeps it monotone."""
    spec, p, data = ns_setup
    groups = list(api.get_param_groups(spec))
    start = p.copy()
    start[0] += 0.2
    start[1:4] += 0.05
    r_off = opt.estimate_steps(spec, data, start[:, None], groups,
                               max_group_iters=3, second_order=False)
    r_on = opt.estimate_steps(spec, data, start[:, None], groups,
                              max_group_iters=3, second_order="exact")
    assert r_on[1] >= r_off[1] - 1e-9
    rep = opt.last_multistart_report()
    assert rep["phase"][rep["best"]] in ("newton", "lbfgs")


@pytest.mark.slow
def test_estimate_windows_second_order(dns_setup):
    spec, p, data = dns_setup
    T = int(data.shape[1])
    raw = np.asarray(_raw_point(spec, p))[None]
    ws = np.asarray([0, 0])
    we = np.asarray([T - 10, T])
    xs0, lls0 = opt.estimate_windows(spec, data, raw, ws, we, max_iters=60,
                                     second_order=False)
    xs1, lls1 = opt.estimate_windows(spec, data, raw, ws, we, max_iters=60,
                                     second_order="fisher")
    assert np.all(np.asarray(lls1) >= np.asarray(lls0) - 1e-6)


@pytest.mark.slow
def test_refit_column_second_order(dns_setup):
    """The scenario lattice's refit column: per-resample re-estimation with
    the cascade armed matches/beats the first-order refit per resample."""
    spec, p, data = dns_setup
    T = int(data.shape[1])
    rng = np.random.default_rng(1)
    idx = np.stack([rng.integers(0, T, size=T) for _ in range(2)])  # (R, T)
    raw = np.asarray(_raw_point(spec, p))[None]
    xs0, lls0 = refit_column(spec, data, idx, raw, max_iters=60,
                             second_order=False)
    xs1, lls1 = refit_column(spec, data, idx, raw, max_iters=60,
                             second_order="fisher")
    assert np.asarray(xs1).shape == (2, 1, spec.n_params)
    assert np.all(np.asarray(lls1) >= np.asarray(lls0) - 1e-6)
    with pytest.raises(ValueError):
        refit_column(spec, data, idx[:, :-1], raw)
