"""Request-path resilience: the serving gateway under deterministic chaos.

Acceptance coverage for docs/DESIGN.md §12 (ISSUE 6):

- (a) with the ``queue_stall`` seam armed the gateway SHEDS offered load
  above capacity — bounded queue depth/memory, structured retry-after
  admission errors — instead of growing without bound, and the admitted
  requests still complete once the stall clears (bounded p99 for admitted);
- (b) a deadline-expired forecast is answered from the service's LAST-GOOD
  snapshot, stale-flagged, bit-identical to ``ServingSnapshot``'s state;
- (c) the gateway's shed/deadline/degraded counters reconcile exactly with
  the closed-loop load generator's request ledger (robustness/loadgen.py).

All chaos is armed with deterministic triggers (``@N`` counts or p=1.0) and
the age/deadline machinery runs on an injected fake clock — no wall-clock
sleeps decide any assertion.
"""

import threading
import time

import numpy as np
import pytest

import yieldfactormodels_jl_tpu as yfm
from tests import oracle
from yieldfactormodels_jl_tpu import serving
from yieldfactormodels_jl_tpu.orchestration import chaos
from yieldfactormodels_jl_tpu.robustness import loadgen

MATS = tuple(np.array([3, 12, 24, 60, 120, 240, 360]) / 12.0)
T_PANEL = 40
T_ORIGIN = 34


@pytest.fixture(scope="module")
def dns_setup():
    rng = np.random.default_rng(7)
    spec, _ = yfm.create_model("1C", MATS, float_type="float64")
    p = oracle.stable_1c_params(spec, np.float64)
    data = oracle.simulate_dns_panel(rng, np.asarray(MATS), T=T_PANEL)
    return spec, p, data


@pytest.fixture(autouse=True)
def _chaos_clean():
    """Every test starts and ends disarmed (the module shares hit counters)."""
    chaos.reset()
    yield
    chaos.reset()


LATTICE = dict(horizons=(4, 8), batch_sizes=(1, 4), scenario_counts=(4, 8))


def _service(dns_setup, **kw):
    spec, p, data = dns_setup
    return serving.YieldCurveService(
        serving.freeze_snapshot(spec, p, data, end=T_ORIGIN),
        lattice=serving.BucketLattice(**LATTICE), **kw)


# ---------------------------------------------------------------------------
# basic flow + counters + isolation
# ---------------------------------------------------------------------------

def test_gateway_answers_match_direct_service_calls(dns_setup):
    spec, p, data = dns_setup
    svc = _service(dns_setup)
    gw = serving.ServingGateway(svc, queue_max=16, queue_age_ms=0.0)
    t_u = gw.submit_update(T_ORIGIN, data[:, T_ORIGIN])
    t_f = gw.submit_forecast(4, quantiles=(0.1, 0.9))
    t_s = gw.submit_scenarios(4, 4, seed=3)
    assert len(gw) == 3
    assert gw.pump() == 3 and len(gw) == 0
    r_u, r_f, r_s = gw.poll(t_u), gw.poll(t_f), gw.poll(t_s)
    assert np.isfinite(r_u["ll"]) and not r_u["stale"]
    assert r_f["means"].shape == (4, spec.N) and 0.1 in r_f["quantiles"]
    assert r_s["paths"].shape == (spec.N, 4, 4)

    # the same requests straight through the service agree exactly (the
    # gateway adds policy, never arithmetic)
    svc2 = _service(dns_setup)
    ll2 = svc2.update(T_ORIGIN, data[:, T_ORIGIN])
    np.testing.assert_allclose(r_u["ll"], ll2, rtol=1e-12)
    np.testing.assert_array_equal(r_f["means"], svc2.forecast(4)["means"])
    np.testing.assert_array_equal(
        r_s["paths"], svc2.scenarios(n=4, h=4, seed=3)["paths"])

    # one report: counters ride health() and latency_summary()
    c = svc.counters.to_dict()
    assert c["admitted"] == 3 and c["completed"] == 3
    assert c["shed"] == c["degraded"] == c["errors"] == 0
    assert svc.health()["requests"] == c
    assert svc.latency_summary()["counters"] == c


def test_poisoned_request_fails_alone(dns_setup):
    """Worker isolation: a request that raises inside dispatch errors ITS
    ticket only — the rest of the drained batch answers normally."""
    spec, p, data = dns_setup
    svc = _service(dns_setup)
    gw = serving.ServingGateway(svc, queue_max=16, queue_age_ms=0.0)
    t_bad = gw.submit_update(0, data[:3, T_ORIGIN])     # wrong length curve
    t_f = gw.submit_forecast(4)
    t_u = gw.submit_update(1, data[:, T_ORIGIN])
    gw.pump()
    with pytest.raises(serving.ServingError) as ei:
        gw.poll(t_bad)
    assert ei.value.stage == "update"
    assert np.isfinite(gw.poll(t_u)["ll"])
    assert np.all(np.isfinite(gw.poll(t_f)["means"]))
    assert svc.counters.errors == 1 and svc.counters.completed == 2


def test_unknown_ticket_is_structured_error(dns_setup):
    gw = serving.ServingGateway(_service(dns_setup))
    with pytest.raises(serving.ServingError) as ei:
        gw.result(999)
    assert ei.value.stage == "gateway"


# ---------------------------------------------------------------------------
# (a) queue_stall: shed, bounded, and admitted requests still finish
# ---------------------------------------------------------------------------

def test_queue_stall_sheds_instead_of_growing_unbounded(dns_setup):
    svc = _service(dns_setup)
    gw = serving.ServingGateway(svc, queue_max=8, queue_age_ms=0.0,
                                queue_stall_s=0.0)
    chaos.configure("queue_stall:1.0")      # every pump cycle stalls
    sheds = []
    for i in range(50):
        try:
            gw.submit_forecast(4)
        except serving.ServingError as e:
            sheds.append(e)
        if i % 10 == 0:
            assert gw.pump() == 0           # stalled: nothing drains
    # bounded: depth pinned at queue_max, everything else shed loudly
    assert len(gw) == 8 and len(sheds) == 42
    assert svc.counters.admitted == 8 and svc.counters.shed == 42
    for e in sheds:
        assert e.stage == "admission"
        assert e.context["retry_after_ms"] > 0  # backoff hint, not a timeout
    # stall clears -> the admitted requests all complete (no loss, no decay)
    chaos.configure(None)
    assert gw.pump() == 8
    assert svc.counters.completed == 8 and svc.counters.errors == 0


def test_stalled_queue_age_sheds_new_arrivals(dns_setup):
    """Head-of-queue age is the second admission limit: a stalled worker
    makes the gateway refuse new work long before the depth bound."""
    clk = {"t": 0.0}
    gw = serving.ServingGateway(_service(dns_setup), queue_max=100,
                                queue_age_ms=50.0, clock=lambda: clk["t"])
    gw.submit_forecast(4)
    clk["t"] += 0.2                          # head is now 200 ms old
    with pytest.raises(serving.ServingError) as ei:
        gw.submit_forecast(4)
    assert ei.value.stage == "admission"
    assert "stalled" in ei.value.detail
    assert gw.counters.shed == 1 and len(gw) == 1


# ---------------------------------------------------------------------------
# (b) deadline -> degraded answer from the last-good snapshot, bit-identical
# ---------------------------------------------------------------------------

def test_deadline_expired_answer_is_last_good_snapshot(dns_setup):
    spec, p, data = dns_setup
    svc = _service(dns_setup)
    clk = {"t": 0.0}
    gw = serving.ServingGateway(svc, queue_max=16, queue_age_ms=0.0,
                                clock=lambda: clk["t"])
    # advance the state so last_good is NOT the boot snapshot — the degraded
    # answer must be the last GOOD state, not wherever the service started
    gw.submit_update(T_ORIGIN, data[:, T_ORIGIN])
    gw.submit_update(T_ORIGIN + 1, data[:, T_ORIGIN + 1])
    gw.pump()
    assert svc.version == 2

    t_dead = gw.submit_forecast(4, deadline_ms=10.0)
    t_live = gw.submit_forecast(4)           # no deadline: same batch, fresh
    clk["t"] += 0.5                          # 500 ms late
    gw.pump()
    out = gw.poll(t_dead)
    assert out["degraded"] and out["stale"] and "deadline" in out["reason"]
    snap = svc.last_good_snapshot
    assert out["version"] == snap.meta.version == 2
    np.testing.assert_array_equal(out["beta"], np.asarray(snap.beta))
    np.testing.assert_array_equal(out["P"], np.asarray(snap.P))
    # ... while the deadline-free request in the same batch got the real answer
    live = gw.poll(t_live)
    assert "degraded" not in live and live["means"].shape == (4, spec.N)
    c = svc.counters
    assert c.deadline == 1 and c.degraded == 1 and c.completed == 3


def test_flush_cost_spike_recovers_instead_of_permanent_degrade(dns_setup):
    """A one-off flush outlier (compile, GC pause) inflates the cost
    estimate; with every request carrying a deadline below it, nothing would
    ever flush to refresh the estimate — the gateway must DECAY it and find
    its way back to fresh answers, not degrade forever."""
    spec, p, data = dns_setup
    svc = _service(dns_setup)
    clk = {"t": 0.0}
    gw = serving.ServingGateway(svc, queue_max=16, queue_age_ms=0.0,
                                clock=lambda: clk["t"])
    gw._flush_cost = 10.0     # the outlier: 10 s "measured" flush
    outs = []
    for _ in range(12):
        t = gw.submit_forecast(4, deadline_ms=100.0)  # live, but under est
        gw.pump()
        outs.append(gw.poll(t))
        if "degraded" not in outs[-1]:
            break
    assert outs[0]["degraded"]                  # spike: degrade, don't stall
    assert "degraded" not in outs[-1]           # decayed: fresh answers again
    assert outs[-1]["means"].shape == (4, spec.N)
    assert gw._flush_cost < 0.1
    assert svc.counters.deadline == len(outs) - 1


def test_env_knob_defaults(dns_setup, monkeypatch):
    monkeypatch.setenv("YFM_SERVE_QUEUE_MAX", "7")
    monkeypatch.setenv("YFM_SERVE_QUEUE_AGE_MS", "123")
    monkeypatch.setenv("YFM_SERVE_DEADLINE_MS", "456")
    gw = serving.ServingGateway(_service(dns_setup))
    assert gw.queue_max == 7
    assert gw.queue_age_ms == 123.0 and gw.deadline_ms == 456.0
    # constructor args win over the environment
    gw2 = serving.ServingGateway(_service(dns_setup), queue_max=3,
                                 queue_age_ms=0.0, deadline_ms=0.0)
    assert gw2.queue_max == 3
    assert gw2.queue_age_ms == 0.0 and gw2.deadline_ms == 0.0


# ---------------------------------------------------------------------------
# (c) closed-loop load: ledger == counters, zero unhandled exceptions
# ---------------------------------------------------------------------------

def test_load_ledger_reconciles_with_service_counters(dns_setup):
    svc = _service(dns_setup)
    # queue_max below the burst size forces deterministic shedding every
    # burst; poison_ticket:@2 degrades exactly one batched ticket; the
    # stall seam drops pump cycles without sleeping (queue_stall_s=0)
    gw = serving.ServingGateway(svc, queue_max=2, queue_age_ms=0.0,
                                queue_stall_s=0.0, slow_update_s=0.0)
    chaos.configure("poison_ticket:@2,queue_stall:@3,slow_update:@2")
    rep = loadgen.run_load(gw, dns_setup[2], duration_s=0.3,
                           offered_qps=400.0, mix=(0.3, 0.5, 0.2),
                           horizon=4, n_scenarios=4, burst=4, seed=0)
    chaos.configure(None)
    c = svc.counters
    # every offered request is accounted exactly once, and the load
    # generator's ledger IS the operator's counter report
    assert rep.offered == rep.ok + rep.degraded + rep.shed + rep.errors \
        + rep.abandoned
    assert rep.abandoned == 0
    assert rep.shed == c.shed > 0            # bursts over the depth bound
    assert rep.degraded == c.degraded == 1   # the poisoned ticket, exactly
    assert rep.ok == c.completed > 0
    assert rep.errors == c.errors == 0
    assert rep.offered == c.admitted + c.shed
    assert rep.p999_ms >= rep.p99_ms >= rep.p50_ms > 0.0


def test_slow_update_seam_injects_latency(dns_setup):
    spec, p, data = dns_setup
    svc = _service(dns_setup)
    gw = serving.ServingGateway(svc, queue_max=4, queue_age_ms=0.0,
                                slow_update_s=0.05)
    gw.submit_update(0, data[:, T_ORIGIN])
    gw.pump()                                # warm the update program
    chaos.configure("slow_update:1.0")
    gw.submit_update(1, data[:, T_ORIGIN + 1])
    t0 = time.perf_counter()
    gw.pump()
    assert time.perf_counter() - t0 >= 0.05  # the injected tail
    assert svc.counters.completed == 2


# ---------------------------------------------------------------------------
# background worker mode
# ---------------------------------------------------------------------------

def test_background_worker_serves_and_stops(dns_setup):
    spec, p, data = dns_setup
    svc = _service(dns_setup)
    gw = serving.ServingGateway(svc, queue_max=16, queue_age_ms=0.0).start()
    try:
        tickets = [gw.submit_update(i, data[:, T_ORIGIN + i]) for i in range(3)]
        tickets.append(gw.submit_forecast(4))
        outs = [gw.result(t, timeout=60.0) for t in tickets]
        assert all(np.isfinite(o["ll"]) for o in outs[:3])
        assert outs[3]["means"].shape == (4, spec.N)
    finally:
        gw.stop()
    assert not any(th.name == "yfm-serving-gateway" and th.is_alive()
                   for th in threading.enumerate())
    assert svc.counters.completed == 4
