"""Loading-matrix golden tests vs closed form / NumPy oracle."""

import numpy as np

from tests import oracle
from yieldfactormodels_jl_tpu.models import loadings as L
from yieldfactormodels_jl_tpu.utils.nn_transform import transform_net_1, transform_net_2


def test_dns_loadings_closed_form(maturities):
    gamma = np.log(0.55)
    Z = np.asarray(L.dns_loadings(gamma, maturities))
    lam = 1e-2 + 0.55
    tau = lam * maturities
    np.testing.assert_allclose(Z[:, 0], 1.0)
    np.testing.assert_allclose(Z[:, 1], (1 - np.exp(-tau)) / tau, rtol=1e-10)
    np.testing.assert_allclose(Z[:, 2], (1 - np.exp(-tau)) / tau - np.exp(-tau), rtol=1e-10)


def test_mlp_curve_matches_oracle(rng, maturities):
    p9 = rng.standard_normal(9)
    got = np.asarray(L.mlp_curve(p9, maturities))
    np.testing.assert_allclose(got, oracle.mlp_curve(p9, maturities), rtol=1e-10)


def test_shape_transforms_match_oracle(rng, maturities):
    for transformed in (True, False):
        raw = rng.standard_normal(len(maturities))
        got1 = np.asarray(transform_net_1(raw, maturities, transformed))
        np.testing.assert_allclose(got1, oracle.transform_net_1(raw, transformed), rtol=1e-9)
        raw2 = rng.standard_normal(len(maturities))
        got2 = np.asarray(transform_net_2(raw2, maturities, transformed))
        np.testing.assert_allclose(
            got2, oracle.transform_net_2(raw2, maturities, transformed), rtol=1e-9
        )


def test_neural_loadings_shape_properties(rng, maturities):
    gamma = rng.standard_normal(18) / 10
    for tb in (True, False):
        Z = np.asarray(L.neural_loadings(gamma, maturities, tb))
        np.testing.assert_allclose(Z[:, 0], 1.0)
        assert Z[0, 1] == 1.0          # slope curve pinned to 1 at short end
        assert Z[-2, 1] == 0.0 and Z[-1, 1] == 0.0
        assert Z[0, 2] == 0.0 and Z[-1, 2] == 0.0   # hump pinned to 0 at ends
        assert np.all(Z[1:-1, 2] >= 0)  # squared ⇒ nonneg
        np.testing.assert_allclose(
            Z, oracle.neural_loadings(gamma, maturities, tb), rtol=1e-9
        )
