"""Amortized estimation (estimation/amortize.py, docs/DESIGN.md §20).

Coverage contract (ISSUE 15): "deepset" surrogate forward/loss parity
against the independent NumPy loops in tests/oracle.py (graftlint YFM007 —
the AMORTIZER_ENGINES registry entry is named here), NaN-panel masking
parity, parameter-recovery calibration at the shared stable points
(likelihood-space: the predicted point must close most of the loglik gap
between the prior mean and the truth), warm-start-matches-or-beats-cold,
the bit-for-bit off switch (``YFM_AMORT`` unset), no-recompile trace
counters, and the serving refit/publish surfaces.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests import oracle
from yieldfactormodels_jl_tpu import create_model
from yieldfactormodels_jl_tpu.estimation import amortize
from yieldfactormodels_jl_tpu.estimation import optimize
from yieldfactormodels_jl_tpu.models import api
from yieldfactormodels_jl_tpu.models.params import (transform_params,
                                                    untransform_params)

MATS = tuple(np.array([3.0, 6.0, 12.0, 24.0, 36.0, 60.0, 84.0, 120.0]) / 12.0)
T_PANEL = 96


@pytest.fixture(scope="module")
def spec():
    s, _ = create_model("1C", MATS, float_type="float64")
    return s


@pytest.fixture(scope="module")
def base_params(spec):
    return oracle.stable_1c_params(spec, dtype=np.float64)


@pytest.fixture(scope="module")
def trained(spec, base_params):
    """One cheaply-trained surrogate shared by the module (train-once is the
    whole point); registered copies are cleaned per test, not here."""
    return amortize.train_amortizer(spec, base_params, T_PANEL, n_rounds=20,
                                    batch=96, steps_per_round=10, lr=1e-2,
                                    prior_scale=0.1)


@pytest.fixture(scope="module")
def heldout(spec, trained):
    """Held-out (draws, panels) the surrogate never trained on."""
    base_raw = trained.info["base_raw"]
    B = 32
    draws = amortize.sample_prior_raw(spec, base_raw, B,
                                      jax.random.PRNGKey(123), 0.1)
    sim = amortize._jitted_sim_batch(spec, T_PANEL, B, False)
    out = sim(jnp.asarray(draws), jax.random.split(jax.random.PRNGKey(321),
                                                   B))
    return np.asarray(out["raw"]), np.asarray(out["panels"])


@pytest.fixture(autouse=True)
def _clean_registry():
    amortize.clear_amortizers()
    yield
    amortize.clear_amortizers()
    os.environ.pop("YFM_AMORT", None)


# ---------------------------------------------------------------------------
# oracle parity ("deepset" forward + masked loss)
# ---------------------------------------------------------------------------

def test_forward_matches_numpy_oracle(spec, rng):
    """The jitted "deepset" forward pass equals the independent NumPy
    per-step loops — including masked (partially-NaN) panels."""
    from yieldfactormodels_jl_tpu import config

    # the registry entry this parity test covers (graftlint YFM007)
    assert "deepset" in config.AMORTIZER_ENGINES
    cfg = amortize.AmortizerConfig()
    params = amortize.init_params(cfg, spec, jax.random.PRNGKey(3))
    Y = oracle.simulate_dns_panel(rng, np.asarray(MATS), T=40)
    Y[:, 7] = np.nan          # whole column unquoted
    Y[2, 19] = np.nan         # partial column → whole column invalid
    params = amortize.set_normalization(params, Y[:, :, None])
    fn = amortize._jitted_forward(cfg, spec, Y.shape[1], 1)
    got = np.asarray(fn(params, jnp.asarray(Y)[:, :, None]))[:, 0]
    want = oracle.amortizer_forward(params, Y)
    np.testing.assert_allclose(got, want, rtol=1e-10, atol=1e-12)
    # all-invalid panel → all-NaN sentinel on BOTH sides, nothing raises
    nanp = np.full_like(Y, np.nan)
    got_nan = np.asarray(fn(params, jnp.asarray(nanp)[:, :, None]))[:, 0]
    assert np.all(~np.isfinite(got_nan))
    assert np.all(~np.isfinite(oracle.amortizer_forward(params, nanp)))


def test_nan_panel_masking_loss_parity(spec, rng):
    """The training loss masks bad samples exactly like the NumPy oracle:
    NaN-poisoned panels carry weight zero, never raise."""
    cfg = amortize.AmortizerConfig()
    params = amortize.init_params(cfg, spec, jax.random.PRNGKey(4))
    B = 6
    panels = np.stack([oracle.simulate_dns_panel(rng, np.asarray(MATS), T=30)
                       for _ in range(B)], axis=0)     # (B, N, T)
    panels[1] = np.nan                                  # dead panel
    panels[3, :, 11] = np.nan                           # one masked column
    targets = rng.standard_normal((B, spec.n_params))
    targets[4] = np.nan                                 # dead target
    params = amortize.set_normalization(params, np.moveaxis(panels, 0, -1))
    got = float(amortize._loss_core(
        cfg, params, jnp.asarray(np.moveaxis(panels, 0, -1)),
        jnp.asarray(targets.T)))
    want = oracle.amortizer_loss(params, panels, targets)
    np.testing.assert_allclose(got, want, rtol=1e-10)


def test_net_target_space_round_trip(spec, base_params):
    """net_targets (δ → steady state μ) and raw_from_net (δ = (I−Φ̂)μ̂) are
    inverses on stationary draws."""
    base_raw = np.asarray(untransform_params(
        spec, jnp.asarray(base_params)), dtype=np.float64)
    draws = amortize.sample_prior_raw(spec, base_raw, 8,
                                      jax.random.PRNGKey(5), 0.1)
    net = amortize.net_targets(spec, draws)             # (P, B)
    assert np.all(np.isfinite(net))
    back = amortize.raw_from_net(spec, net.T)           # (B, P)
    np.testing.assert_allclose(back, draws.T, rtol=1e-9, atol=1e-9)


# ---------------------------------------------------------------------------
# parameter-recovery calibration (likelihood space — see DESIGN §20 for why
# raw-δ MSE is the wrong metric: its posterior noise is unknowable Φ·μ)
# ---------------------------------------------------------------------------

def test_parameter_recovery_calibration(spec, base_params, trained, heldout):
    tgts, panels = heldout                              # (P, B), (N, T, B)
    B = tgts.shape[1]
    preds = trained.predict_raw_batch(np.moveaxis(panels, -1, 0))
    ok = np.all(np.isfinite(preds), axis=1)
    assert ok.mean() > 0.9                              # sims are stationary

    loss_b = jax.jit(jax.vmap(lambda p, d: api.get_loss(spec, p, d),
                              in_axes=(0, 0)))
    cons = jax.vmap(lambda r: transform_params(spec, r))
    pan = jnp.asarray(np.moveaxis(panels, -1, 0))
    ll_pred = np.asarray(loss_b(cons(jnp.asarray(preds)), pan))
    ll_true = np.asarray(loss_b(cons(jnp.asarray(tgts.T)), pan))
    ll_base = np.asarray(loss_b(
        jnp.tile(jnp.asarray(base_params)[None], (B, 1)), pan))
    fin = ok & np.isfinite(ll_pred) & np.isfinite(ll_true) \
        & np.isfinite(ll_base)
    assert fin.sum() >= 20
    # calibration: the one-forward-pass estimate closes most of the loglik
    # gap between the prior-mean point and the simulating truth...
    gap_closed = (ll_pred[fin] - ll_base[fin]).mean() \
        / (ll_true[fin] - ll_base[fin]).mean()
    assert gap_closed > 0.5, f"surrogate closes only {gap_closed:.2%}"
    # ...and beats the prior point on nearly every held-out panel
    assert (ll_pred[fin] > ll_base[fin]).mean() > 0.9
    # raw-space calibration where the parameter IS identifiable: the λ
    # driver's MSE must shrink well below the prior's
    lo, hi = spec.layout["gamma"]
    base_raw = trained.info["base_raw"]
    r = np.mean((preds[ok, lo:hi] - tgts.T[ok, lo:hi]) ** 2) \
        / np.mean((base_raw[None, lo:hi] - tgts.T[ok, lo:hi]) ** 2)
    assert r < 0.7, f"λ recovery ratio {r:.2f}"


# ---------------------------------------------------------------------------
# warm-start wiring (estimate / report tags / off switch)
# ---------------------------------------------------------------------------

def _panel_and_starts(spec, trained, seed=55):
    base_raw = trained.info["base_raw"]
    draw = amortize.sample_prior_raw(spec, base_raw, 1,
                                     jax.random.PRNGKey(seed), 0.1)[:, 0]
    data = np.asarray(api.simulate(
        spec, transform_params(spec, jnp.asarray(draw)), T_PANEL,
        jax.random.PRNGKey(seed + 1))["data"])
    rng = np.random.default_rng(7)
    raws = base_raw[None] + 0.05 * rng.standard_normal((2, base_raw.shape[0]))
    starts = np.stack([np.asarray(transform_params(spec, jnp.asarray(r)))
                       for r in raws], axis=1)          # (P, S)
    return data, starts


@pytest.mark.slow
def test_warm_start_matches_or_beats_cold(spec, trained):
    data, starts = _panel_and_starts(spec, trained)
    _, ll_cold, _, _ = optimize.estimate(spec, data, starts, max_iters=300,
                                         g_tol=1e-5, f_abstol=1e-8,
                                         warm_start=False)
    _, ll_warm, _, _ = optimize.estimate(spec, data, starts, max_iters=300,
                                         g_tol=1e-5, f_abstol=1e-8,
                                         warm_start=trained,
                                         second_order="fisher")
    rep = optimize.last_multistart_report()
    assert ll_warm >= ll_cold - 1e-3        # ISSUE 15 acceptance tolerance
    assert any(p.startswith("amortized") for p in rep["phase"])
    # the anchor row (the caller's first start) is never tagged amortized
    assert not rep["phase"][-1].startswith("amortized")


def test_off_switch_is_bit_for_bit(spec, trained):
    """YFM_AMORT unset + a REGISTERED surrogate: estimate() must reproduce
    the historical path bit-for-bit (no amortizer code runs — pinned by the
    forward-pass trace counter)."""
    data, starts = _panel_and_starts(spec, trained)
    amortize.register_amortizer(trained)
    amortize.reset_trace_counts()
    kw = dict(max_iters=40, g_tol=1e-5, f_abstol=1e-8)
    r_default = optimize.estimate(spec, data, starts, **kw)
    r_off = optimize.estimate(spec, data, starts, warm_start=False, **kw)
    assert amortize.trace_counts["forward"] == 0
    assert r_default[1] == r_off[1]
    np.testing.assert_array_equal(r_default[2], r_off[2])
    assert not any(p.startswith("amortized")
                   for p in optimize.last_multistart_report()["phase"])


def test_env_knob_arms_registered_amortizer(spec, trained):
    data, starts = _panel_and_starts(spec, trained)
    amortize.register_amortizer(trained)
    os.environ["YFM_AMORT"] = "1"
    try:
        kw = optimize.resolve_estimation_env()
        assert kw["warm_start"] is True
        optimize.estimate(spec, data, starts, max_iters=40, g_tol=1e-4,
                          f_abstol=1e-8)
        assert any(p.startswith("amortized")
                   for p in optimize.last_multistart_report()["phase"])
    finally:
        os.environ.pop("YFM_AMORT", None)
    # knob armed but NOTHING registered: quietly historical (other specs
    # must not break when the knob is set process-wide)
    amortize.clear_amortizers()
    os.environ["YFM_AMORT"] = "1"
    try:
        optimize.estimate(spec, data, starts, max_iters=40, g_tol=1e-4,
                          f_abstol=1e-8)
        assert not any(p.startswith("amortized")
                       for p in optimize.last_multistart_report()["phase"])
    finally:
        os.environ.pop("YFM_AMORT", None)


def test_sentinel_prediction_falls_back_to_spray(spec, trained):
    """A non-finite surrogate prediction (all-NaN panel) keeps the caller's
    historical start spray — sentinel in, historical behavior out."""
    data = np.full((spec.N, 30), np.nan)
    assert trained.starts(data) is None
    fb = np.zeros(spec.n_params)
    sb = trained.starts_batch(np.stack([data, data]), fallback_raw=fb)
    assert sb.shape[0] == 2 and np.allclose(sb[:, 0, :], 0.0)


def test_no_recompile_across_predicts_and_rounds(spec, trained, heldout):
    _, panels = heldout
    # a panel length nothing else in the module uses: the first call must
    # trace, the repeats must NOT (the lru-cached program is shared)
    panels = panels[:, :77, :]
    amortize.reset_trace_counts()
    for i in range(3):
        trained.predict_raw(panels[:, :, i])
    assert amortize.trace_counts["forward"] == 1
    trained.predict_raw_batch(np.moveaxis(panels, -1, 0))
    assert amortize.trace_counts["forward"] == 2  # new batch size: one more
    # simulation program: one trace across repeated rounds (donated draws)
    amortize.reset_trace_counts()
    sim = amortize._jitted_sim_batch(spec, 24, 4, True)
    for i in range(3):
        draws = amortize.sample_prior_raw(
            spec, trained.info["base_raw"], 4, jax.random.PRNGKey(i), 0.05)
        sim(jnp.asarray(draws), jax.random.split(jax.random.PRNGKey(i), 4))
    assert amortize.trace_counts["sim"] == 1


# ---------------------------------------------------------------------------
# refit column (per-resample warm starts)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_refit_column_warm_matches_or_beats_cold(spec, trained):
    from yieldfactormodels_jl_tpu.estimation.bootstrap import (
        moving_block_indices)
    from yieldfactormodels_jl_tpu.estimation.scenario import refit_column

    data, starts = _panel_and_starts(spec, trained, seed=91)
    idx = np.asarray(moving_block_indices(jax.random.PRNGKey(2), T_PANEL,
                                          12, 3))
    raw_starts = np.stack([np.asarray(untransform_params(
        spec, jnp.asarray(starts[:, j]))) for j in range(starts.shape[1])])
    xs_c, ll_c = refit_column(spec, data, idx, raw_starts, max_iters=60,
                              warm_start=False)
    xs_w, ll_w = refit_column(spec, data, idx, raw_starts, max_iters=60,
                              warm_start=trained)
    best_c = np.max(np.where(np.isfinite(ll_c), ll_c, -np.inf), axis=1)
    best_w = np.max(np.where(np.isfinite(ll_w), ll_w, -np.inf), axis=1)
    assert np.asarray(xs_w).shape[0] == idx.shape[0]
    assert np.all(best_w >= best_c - 1e-3)


# ---------------------------------------------------------------------------
# serving surfaces
# ---------------------------------------------------------------------------

def test_service_refit_updates_params_and_version(spec, base_params,
                                                  trained):
    from yieldfactormodels_jl_tpu import serving

    data, _ = _panel_and_starts(spec, trained, seed=33)
    snap = serving.freeze_snapshot(spec, base_params, data)
    svc = serving.YieldCurveService(snap)
    with pytest.raises(serving.ServingError):
        svc.refit(data)                    # nothing registered → structural
    v0 = svc.version
    ll = svc.refit(data, amortizer=trained)
    assert np.isfinite(ll)
    assert svc.version > v0
    assert not np.allclose(np.asarray(svc.snapshot.params),
                           np.asarray(base_params))
    # the refit parameters must fit the history at least as well as the
    # boot parameters did
    ll_base = float(api.get_loss(spec, jnp.asarray(base_params),
                                 jnp.asarray(data)))
    assert ll > ll_base


def test_store_publish_refit_rewrites_live_slot(spec, base_params, trained):
    from yieldfactormodels_jl_tpu import serving
    from yieldfactormodels_jl_tpu.serving.store import ShardedStateStore

    data, _ = _panel_and_starts(spec, trained, seed=44)
    snap = serving.freeze_snapshot(spec, base_params, data)
    store = ShardedStateStore(spec, n_shards=2, shard_capacity=4)
    key = store.register(snap)
    raw, ll = amortize.amortized_refit(spec, data, amortizer=trained,
                                       polish_iters=1)
    params = np.asarray(transform_params(spec, jnp.asarray(raw)))
    out = store.publish_refit(key, params, history=data)
    assert out["version"] == snap.meta.version + 1
    live = store.snapshot_of(key)
    np.testing.assert_allclose(np.asarray(live.params), params, rtol=1e-12)
    with pytest.raises(serving.ServingError):
        store.publish_refit(("nope", 1), params)


def test_gateway_refit_deadline_degrades(spec, base_params, trained):
    from yieldfactormodels_jl_tpu import serving
    from yieldfactormodels_jl_tpu.serving.gateway import ServingGateway

    data, _ = _panel_and_starts(spec, trained, seed=66)
    snap = serving.freeze_snapshot(spec, base_params, data)
    svc = serving.YieldCurveService(snap)
    gw = ServingGateway(svc, queue_age_ms=0.0)
    out = gw.refit(data, amortizer=trained)
    assert out["kind"] == "refit" and np.isfinite(out["ll"])
    # measured cost now in the EWMA: an impossible budget answers degraded
    # from the last-good snapshot instead of blowing the deadline
    out2 = gw.refit(data, deadline_ms=1e-6, amortizer=trained)
    assert out2.get("degraded") and out2.get("stale")
    assert svc.counters.degraded >= 1
