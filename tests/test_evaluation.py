"""Diebold–Mariano test: size and power on simulated error series."""

import numpy as np
import pytest

from yieldfactormodels_jl_tpu.utils.evaluation import diebold_mariano


def test_dm_size_under_null():
    """Equal-accuracy iid errors ⇒ DM ≈ N(0,1): the rejection rate at the
    5% level stays near 5% across replications."""
    rng = np.random.default_rng(0)
    rejections = 0
    R = 200
    for _ in range(R):
        e1 = rng.standard_normal(200)
        e2 = rng.standard_normal(200)
        stat, p = diebold_mariano(e1, e2, h=1)
        rejections += p < 0.05
    assert 0.01 < rejections / R < 0.12  # ±binomial noise around 0.05


def test_dm_power_and_sign():
    """A clearly worse model 2 ⇒ large negative statistic, tiny p-value."""
    rng = np.random.default_rng(1)
    e1 = rng.standard_normal(300)
    e2 = 2.0 * rng.standard_normal(300)
    stat, p = diebold_mariano(e1, e2, h=1)
    assert stat < -3 and p < 1e-3


def test_dm_multivariate_and_horizon():
    """(T, N) errors reduce over maturities; h > 1 engages the HAC lags +
    Harvey correction and must keep the conclusion (sign, significance) on a
    clear accuracy gap — the exact magnitude depends on sample
    autocovariances, so only sign/level are pinned."""
    rng = np.random.default_rng(2)
    e1 = rng.standard_normal((150, 8))
    e2 = 1.5 * rng.standard_normal((150, 8))
    s1, p1 = diebold_mariano(e1, e2, h=1)
    s12, p12 = diebold_mariano(e1, e2, h=12)
    assert s1 < 0 and p1 < 0.05
    assert np.sign(s12) == np.sign(s1) and p12 < 0.05


def test_dm_interior_nans_keep_alignment():
    """Interior NaNs (failed windows) must not collapse the HAC lag spacing:
    the statistic with a few masked periods stays near the full-sample one,
    NOT near the compacted-series one computed on a scrambled lag grid."""
    rng = np.random.default_rng(3)
    T = 240
    base1 = rng.standard_normal(T)
    base2 = 1.4 * rng.standard_normal(T)
    # strongly autocorrelated differential so lag alignment matters at h=12
    ar = np.zeros(T)
    for t in range(1, T):
        ar[t] = 0.9 * ar[t - 1] + rng.standard_normal()
    e1, e2 = base1 + ar, base2 + ar
    s_full, _ = diebold_mariano(e1, e2, h=12)
    e1m, e2m = e1.copy(), e2.copy()
    e1m[40:44] = np.nan
    e2m[150] = np.nan
    s_mask, _ = diebold_mariano(e1m, e2m, h=12)
    assert np.isfinite(s_mask)
    assert abs(s_mask - s_full) < 0.15 * abs(s_full) + 0.05


def test_dm_degenerate_inputs():
    e = np.zeros(50)
    stat, p = diebold_mariano(e, e, h=1)  # constant differential ⇒ NaN
    assert np.isnan(stat) and np.isnan(p)
    with pytest.raises(ValueError, match="shapes"):
        diebold_mariano(np.zeros(10), np.zeros(11))
    with pytest.raises(ValueError, match="loss"):
        diebold_mariano(np.zeros(10), np.ones(10), loss="huber")
