"""Diebold–Mariano test: size and power on simulated error series."""

import numpy as np
import pytest

from yieldfactormodels_jl_tpu.utils.evaluation import diebold_mariano


def test_dm_size_under_null():
    """Equal-accuracy iid errors ⇒ DM ≈ N(0,1): the rejection rate at the
    5% level stays near 5% across replications."""
    rng = np.random.default_rng(0)
    rejections = 0
    R = 200
    for _ in range(R):
        e1 = rng.standard_normal(200)
        e2 = rng.standard_normal(200)
        stat, p = diebold_mariano(e1, e2, h=1)
        rejections += p < 0.05
    assert 0.01 < rejections / R < 0.12  # ±binomial noise around 0.05


def test_dm_power_and_sign():
    """A clearly worse model 2 ⇒ large negative statistic, tiny p-value."""
    rng = np.random.default_rng(1)
    e1 = rng.standard_normal(300)
    e2 = 2.0 * rng.standard_normal(300)
    stat, p = diebold_mariano(e1, e2, h=1)
    assert stat < -3 and p < 1e-3


def test_dm_multivariate_and_horizon():
    """(T, N) errors reduce over maturities; h > 1 engages the HAC lags +
    Harvey correction and must keep the conclusion (sign, significance) on a
    clear accuracy gap — the exact magnitude depends on sample
    autocovariances, so only sign/level are pinned."""
    rng = np.random.default_rng(2)
    e1 = rng.standard_normal((150, 8))
    e2 = 1.5 * rng.standard_normal((150, 8))
    s1, p1 = diebold_mariano(e1, e2, h=1)
    s12, p12 = diebold_mariano(e1, e2, h=12)
    assert s1 < 0 and p1 < 0.05
    assert np.sign(s12) == np.sign(s1) and p12 < 0.05


def test_dm_interior_nans_keep_alignment():
    """Interior NaNs (failed windows) must not collapse the HAC lag spacing:
    the statistic with a few masked periods stays near the full-sample one,
    NOT near the compacted-series one computed on a scrambled lag grid."""
    rng = np.random.default_rng(3)
    T = 240
    base1 = rng.standard_normal(T)
    base2 = 1.4 * rng.standard_normal(T)
    # strongly autocorrelated differential so lag alignment matters at h=12
    ar = np.zeros(T)
    for t in range(1, T):
        ar[t] = 0.9 * ar[t - 1] + rng.standard_normal()
    e1, e2 = base1 + ar, base2 + ar
    s_full, _ = diebold_mariano(e1, e2, h=12)
    e1m, e2m = e1.copy(), e2.copy()
    e1m[40:44] = np.nan
    e2m[150] = np.nan
    s_mask, _ = diebold_mariano(e1m, e2m, h=12)
    assert np.isfinite(s_mask)
    assert abs(s_mask - s_full) < 0.15 * abs(s_full) + 0.05


def test_dm_degenerate_inputs():
    e = np.zeros(50)
    stat, p = diebold_mariano(e, e, h=1)  # constant differential ⇒ NaN
    assert np.isnan(stat) and np.isnan(p)
    with pytest.raises(ValueError, match="shapes"):
        diebold_mariano(np.zeros(10), np.zeros(11))
    with pytest.raises(ValueError, match="loss"):
        diebold_mariano(np.zeros(10), np.ones(10), loss="huber")


def test_crps_matches_numerical_integration():
    """Closed form vs the defining integral ∫(F(x) − 1{x ≥ y})² dx computed
    by independent NumPy quadrature (CLAUDE.md oracle rule)."""
    from scipy.special import ndtr

    from yieldfactormodels_jl_tpu.utils.evaluation import crps_gaussian

    rng = np.random.default_rng(0)
    for _ in range(5):
        mu, sd = rng.normal(), np.exp(rng.normal())
        y = mu + sd * rng.normal() * 2
        lo, hi = mu - 12 * sd, mu + 12 * sd
        # split at the indicator's jump so trapezoid converges O(Δx²);
        # np.trapezoid is numpy>=2 — fall back for the declared 1.24 floor
        trap = getattr(np, "trapezoid", None) or np.trapz
        xs1 = np.linspace(lo, min(y, hi), 100001)
        xs2 = np.linspace(max(y, lo), hi, 100001)
        want = (trap(ndtr((xs1 - mu) / sd) ** 2, xs1)
                + trap((ndtr((xs2 - mu) / sd) - 1.0) ** 2, xs2))
        got = float(crps_gaussian(mu, sd, y))
        np.testing.assert_allclose(got, want, rtol=1e-6)


def test_crps_properties_and_density_pipeline():
    """Sharper correct densities score better; degenerate sd and NaN
    outcomes go NaN; scores of forecast_density feed diebold_mariano."""
    import jax
    import jax.numpy as jnp

    import yieldfactormodels_jl_tpu as yfm
    from tests.oracle import stable_1c_params
    from yieldfactormodels_jl_tpu.utils.evaluation import crps_gaussian

    rng = np.random.default_rng(1)
    y = rng.normal(size=500)
    sharp = crps_gaussian(0.0, 1.0, y).mean()       # the true density
    blunt = crps_gaussian(0.0, 4.0, y).mean()       # too wide
    biased = crps_gaussian(2.0, 1.0, y).mean()      # wrong mean
    assert sharp < blunt and sharp < biased
    assert np.isnan(crps_gaussian(0.0, 0.0, 1.0))
    assert np.isnan(crps_gaussian(0.0, 1.0, np.nan))

    mats = tuple(np.array([3, 12, 36, 84, 180, 360]) / 12.0)
    spec, _ = yfm.create_model("1C", mats, float_type="float64")
    p = jnp.asarray(stable_1c_params(spec, dtype=np.float64))
    sim = yfm.simulate(spec, p, T=60, key=jax.random.PRNGKey(7))
    data = np.asarray(sim["data"])
    fd = yfm.forecast_density(spec, p, data, 3, end=50)
    m = np.asarray(fd["means"])
    s = np.sqrt(np.diagonal(np.asarray(fd["covs"]), axis1=1, axis2=2))
    scores = crps_gaussian(m, s, data[:, 50:53].T)
    assert scores.shape == (3, len(mats)) and np.isfinite(scores).all()


def test_log_predictive_score_matches_oracle():
    """Cholesky-whitened library form vs the oracle's explicit inv/slogdet
    route, over random PSD covariances (CLAUDE.md oracle rule)."""
    from tests.oracle import gaussian_log_score
    from yieldfactormodels_jl_tpu.utils.evaluation import log_predictive_score

    rng = np.random.default_rng(4)
    N = 5
    means = rng.normal(size=(3, 4, N))
    A = rng.normal(size=(3, 4, N, N))
    covs = A @ np.swapaxes(A, -1, -2) + 0.5 * np.eye(N)
    ys = rng.normal(size=(3, 4, N))
    got = log_predictive_score(means, covs, ys)
    assert got.shape == (3, 4)
    for i in range(3):
        for j in range(4):
            np.testing.assert_allclose(
                got[i, j], gaussian_log_score(means[i, j], covs[i, j],
                                              ys[i, j]), rtol=1e-10)


def test_log_predictive_score_sentinels_and_sharpness():
    """Non-PSD / non-finite inputs score NaN (never raise); the true density
    outscores a biased and an overdispersed rival on average."""
    from yieldfactormodels_jl_tpu.utils.evaluation import log_predictive_score

    rng = np.random.default_rng(5)
    N = 4
    eye = np.eye(N)
    assert np.isnan(log_predictive_score(np.zeros(N), -eye, np.zeros(N)))
    assert np.isnan(log_predictive_score(np.full(N, np.nan), eye, np.zeros(N)))
    assert np.isnan(log_predictive_score(np.zeros(N), eye,
                                         np.full(N, np.nan)))
    y = rng.normal(size=(500, N))
    true = log_predictive_score(np.zeros(N), eye, y).mean()
    biased = log_predictive_score(np.full(N, 1.5), eye, y).mean()
    wide = log_predictive_score(np.zeros(N), 9.0 * eye, y).mean()
    assert true > biased and true > wide  # higher is better


def test_crps_sample_matches_oracle_and_closed_form():
    """Ensemble CRPS: the sorted-spacings implementation equals the defining
    double loop, and a large Gaussian ensemble converges to the closed-form
    ``crps_gaussian``."""
    from tests.oracle import crps_sample_naive
    from yieldfactormodels_jl_tpu.utils.evaluation import (crps_gaussian,
                                                           crps_sample)

    rng = np.random.default_rng(6)
    for m in (1, 2, 7, 40):
        x = rng.normal(size=m)
        y = rng.normal()
        np.testing.assert_allclose(float(crps_sample(x, y)),
                                   crps_sample_naive(x, y), rtol=1e-12)
    # broadcast shape: draws on the trailing (lane) axis, like fan paths
    paths = rng.normal(size=(3, 5, 2, 64))
    ys = rng.normal(size=(3, 5, 2))
    got = crps_sample(paths, ys)
    assert got.shape == (3, 5, 2)
    np.testing.assert_allclose(got[1, 2, 0],
                               crps_sample_naive(paths[1, 2, 0], ys[1, 2, 0]),
                               rtol=1e-12)
    # convergence to the Gaussian closed form
    big = rng.normal(loc=0.3, scale=1.7, size=20000)
    approx = float(crps_sample(big, 0.8))
    exact = float(crps_gaussian(0.3, 1.7, 0.8))
    np.testing.assert_allclose(approx, exact, rtol=2e-2)
    # NaN draws propagate
    bad = big.copy()
    bad[3] = np.nan
    assert np.isnan(crps_sample(bad, 0.0))
