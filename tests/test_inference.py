"""Observed-information standard errors: finite-difference parity + sanity
on a true MLE (1C Kalman fitted to its own DGP — tests/oracle.py simulator)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from yieldfactormodels_jl_tpu import create_model, get_loss
from yieldfactormodels_jl_tpu.estimation import optimize
from yieldfactormodels_jl_tpu.estimation.inference import mle_standard_errors
from yieldfactormodels_jl_tpu.models.params import (transform_params,
                                                    untransform_params)

from tests.oracle import simulate_dns_panel

MATS = tuple(np.array([3, 6, 12, 24, 36, 60, 120, 240, 360]) / 12.0)


@pytest.fixture(scope="module")
def fitted_1c():
    rng = np.random.default_rng(7)
    data = simulate_dns_panel(rng, np.asarray(MATS), T=150)
    spec, _ = create_model("1C", MATS, float_type="float64")
    # start at the DGP truth (λ=0.5, Φ diag (0.95,0.9,0.85), state sd 0.1,
    # obs var 4e-4; the +5 level shift moves δ₁ to 0.3 + 0.05·5 = 0.55)
    p = np.zeros(spec.n_params)
    p[spec.layout["gamma"][0]] = np.log(0.49)
    p[spec.layout["obs_var"][0]] = 4e-4
    a, _ = spec.layout["chol"]
    rows, cols = spec.chol_indices
    for k, (r, c) in enumerate(zip(rows, cols)):
        p[a + k] = 0.1 if r == c else 0.0
    b0, b1 = spec.layout["delta"]
    p[b0:b1] = [0.55, -0.1, 0.05]
    b0, b1 = spec.layout["phi"]
    p[b0:b1] = np.diag([0.95, 0.9, 0.85]).reshape(-1)
    # polish with LBFGS restarts (each restart resets the memory pairs):
    # the ΔLL stop can park ~0.5 SE from the optimum after one pass
    best = p
    for _ in range(3):
        _, ll, best, conv = optimize.estimate(spec, data,
                                              np.asarray(best)[:, None],
                                              max_iters=800)
    assert conv.converged and np.isfinite(ll)
    return spec, np.asarray(best), data


def test_se_all_finite_and_recovers_lambda(fitted_1c):
    spec, best, data = fitted_1c
    se, cov, cov_raw = mle_standard_errors(spec, best, data)
    assert np.isfinite(se).all(), se
    assert (se > 0).all()
    np.testing.assert_allclose(cov, cov.T, rtol=1e-10, atol=1e-12)
    # λ̂ ± 3·SE covers the DGP truth 0.5 (delta method through λ = 1e-2 + e^γ,
    # dλ/dγ = e^γ)
    lam_hat = 1e-2 + np.exp(best[0])
    se_lam = np.exp(best[0]) * se[0]
    assert abs(lam_hat - 0.5) < 3 * se_lam + 1e-9


def test_sandwich_se_close_to_hessian_on_wellspecified_dgp(fitted_1c):
    """Information equality: on a correctly-specified Gaussian DGP the
    sandwich H⁻¹BH⁻¹ and the plain H⁻¹ agree up to sampling noise.  Also the
    score contributions must sum to ≈0 at the optimum (first-order cond.)."""
    from yieldfactormodels_jl_tpu.estimation.inference import (
        _jitted_score_contributions)
    from yieldfactormodels_jl_tpu.models.params import untransform_params as utp

    spec, best, data = fitted_1c
    se_h, _, cov_raw = mle_standard_errors(spec, best, data, kind="hessian")
    se_s, cov_s, _ = mle_standard_errors(spec, best, data, kind="sandwich")
    assert np.isfinite(se_s).all()
    np.testing.assert_allclose(cov_s, cov_s.T, rtol=1e-10, atol=1e-12)
    ratio = se_s / se_h
    assert np.all(ratio > 0.3) and np.all(ratio < 3.0), ratio
    S = np.asarray(_jitted_score_contributions(spec, data.shape[1], "joint")(
        jnp.asarray(np.asarray(utp(spec, jnp.asarray(best)))),
        jnp.asarray(data), jnp.asarray(0), jnp.asarray(data.shape[1])))
    # the fit converges on a ΔLL criterion, so the summed score is small but
    # not machine-zero; what matters for inference is that the implied Newton
    # step is well inside one standard error in every direction
    newton = cov_raw @ S.sum(axis=0)
    assert np.all(np.abs(newton) < 0.5 * np.sqrt(np.diagonal(cov_raw))), newton


def test_sandwich_engine_univariate_matches_joint(fitted_1c):
    """The univariate (Cholesky-free) per-step score decomposition must give
    the same sandwich SEs as the joint engine (same algebra, f64 tight);
    moment-less engines raise a clear error."""
    import pytest
    spec, best, data = fitted_1c
    se_j, cov_j, _ = mle_standard_errors(spec, best, data, kind="sandwich",
                                         engine="joint")
    se_u, cov_u, _ = mle_standard_errors(spec, best, data, kind="sandwich",
                                         engine="univariate")
    np.testing.assert_allclose(se_u, se_j, rtol=1e-6)
    np.testing.assert_allclose(cov_u, cov_j, rtol=1e-6, atol=1e-14)
    with pytest.raises(ValueError, match="per-step loglik decomposition"):
        mle_standard_errors(spec, best, data, kind="sandwich", engine="sqrt")


def test_score_contributions_match_numpy_oracle_fd(fitted_1c):
    """Independent-oracle parity (CLAUDE.md rule) for the per-step score
    kernel: each column of S must match central finite differences of the
    NumPy per-step loglik (tests/oracle.kalman_filter_loglik_steps)."""
    from yieldfactormodels_jl_tpu.estimation.inference import (
        _jitted_score_contributions)
    from yieldfactormodels_jl_tpu.models.params import unpack_kalman
    from tests import oracle

    spec, best, data = fitted_1c
    raw = np.asarray(untransform_params(spec, jnp.asarray(best)))
    T = data.shape[1]
    S = np.asarray(_jitted_score_contributions(spec, T, "joint")(
        jnp.asarray(raw), jnp.asarray(data), jnp.asarray(0), jnp.asarray(T)))

    def steps_oracle(r):
        kp = unpack_kalman(spec, transform_params(spec, jnp.asarray(r)))
        Z = oracle.dns_loadings(float(kp.gamma[0]), np.asarray(MATS))
        return oracle.kalman_filter_loglik_steps(
            Z, np.asarray(kp.Phi), np.asarray(kp.delta),
            np.asarray(kp.Omega_state), float(kp.obs_var), data)

    eps = 1e-6
    for j in [0, 1, spec.layout["delta"][0], spec.layout["phi"][0]]:
        e = np.zeros_like(raw)
        e[j] = eps
        col_fd = (steps_oracle(raw + e) - steps_oracle(raw - e)) / (2 * eps)
        np.testing.assert_allclose(S[:, j], col_fd, rtol=2e-4,
                                   atol=1e-6 * np.abs(col_fd).max() + 1e-8,
                                   err_msg=f"score column {j}")


def test_sandwich_rejects_non_kalman(maturities):
    import pytest as _pytest
    spec, _ = create_model("NS", tuple(maturities), float_type="float64")
    with _pytest.raises(ValueError, match="sandwich"):
        mle_standard_errors(spec, np.zeros(spec.n_params),
                            np.zeros((len(maturities), 10)), kind="sandwich")


def test_se_matches_finite_difference_hessian(fitted_1c):
    spec, best, data = fitted_1c
    se, cov, cov_raw = mle_standard_errors(spec, best, data)
    raw = np.asarray(untransform_params(spec, jnp.asarray(best)))
    jdata = jnp.asarray(data)

    g = jax.jit(jax.grad(
        lambda r: -get_loss(spec, transform_params(spec, r), jdata)))
    eps = 1e-5
    P = raw.shape[0]
    H_fd = np.zeros((P, P))
    for j in range(P):
        e = np.zeros(P)
        e[j] = eps
        H_fd[:, j] = (np.asarray(g(jnp.asarray(raw + e)))
                      - np.asarray(g(jnp.asarray(raw - e)))) / (2 * eps)
    H_fd = 0.5 * (H_fd + H_fd.T)
    cov_fd = np.linalg.inv(H_fd)
    J = np.asarray(jax.jacobian(
        lambda r: transform_params(spec, r))(jnp.asarray(raw)))
    se_fd = np.sqrt(np.diagonal(J @ cov_fd @ J.T))
    np.testing.assert_allclose(se, se_fd, rtol=5e-3)


def test_hessian_matches_numpy_oracle_fd(fitted_1c):
    """Independent-oracle parity (CLAUDE.md rule): the AD Hessian must match
    second-order central differences of the NUMPY oracle loglik — a path that
    shares no AD machinery or scan kernel with the library."""
    from yieldfactormodels_jl_tpu.estimation.inference import _jitted_information
    from yieldfactormodels_jl_tpu.models.params import unpack_kalman
    from tests import oracle

    spec, best, data = fitted_1c
    raw = np.asarray(untransform_params(spec, jnp.asarray(best)))
    H_ad, _ = _jitted_information(spec, data.shape[1])(
        jnp.asarray(raw), jnp.asarray(data), jnp.asarray(0),
        jnp.asarray(data.shape[1]))
    H_ad = 0.5 * (np.asarray(H_ad) + np.asarray(H_ad).T)

    def nll_oracle(r):
        kp = unpack_kalman(spec, transform_params(spec, jnp.asarray(r)))
        Z = oracle.dns_loadings(float(kp.gamma[0]), np.asarray(MATS))
        return -oracle.kalman_filter_loglik(
            Z, np.asarray(kp.Phi), np.asarray(kp.delta),
            np.asarray(kp.Omega_state), float(kp.obs_var), data)

    # spot-check a representative sub-block (γ, obs-var, δ₁, Φ₁₁): the full
    # 20×20 4-point stencil would be ~1,600 oracle passes
    idx = [0, 1, spec.layout["delta"][0], spec.layout["phi"][0]]
    eps = 1e-4
    for a, i in enumerate(idx):
        for j in idx[a:]:
            ei = np.zeros_like(raw); ei[i] = eps
            ej = np.zeros_like(raw); ej[j] = eps
            h = (nll_oracle(raw + ei + ej) - nll_oracle(raw + ei - ej)
                 - nll_oracle(raw - ei + ej) + nll_oracle(raw - ei - ej)) / (4 * eps * eps)
            np.testing.assert_allclose(
                H_ad[i, j], h, rtol=2e-3, atol=1e-4 * abs(H_ad[i, j]) + 1e-3,
                err_msg=f"H[{i},{j}]")
