"""simulate(): generative sampling from Kalman-family models.

Checks the simulator against the model's own analytic implications (not
another JAX path): unconditional state moments from the filters'
``init_state`` algebra, measurement-noise scale, SV variance inflation,
and a full round trip — parameters estimated on a simulated panel recover
the simulating λ within sampling error.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import yieldfactormodels_jl_tpu as yfm

from tests.oracle import stable_1c_params, stable_tvl_params

MATS = tuple(np.array([3, 12, 36, 84, 180, 360]) / 12.0)


def test_unconditional_moments_match_numpy_oracle(rng):
    """Long-run sample mean/cov of the simulated state must match the
    INDEPENDENT NumPy unconditional moments (oracle.kalman_init on matrices
    built from the layout in NumPy — CLAUDE.md oracle rule, so a shared
    Lyapunov/reshape bug in the JAX side cannot cancel)."""
    from tests import oracle

    spec, _ = yfm.create_model("1C", MATS, float_type="float64")
    p = stable_1c_params(spec, dtype=np.float64)
    out = yfm.simulate(spec, jnp.asarray(p), T=20000,
                       key=jax.random.PRNGKey(0))
    states = np.asarray(out["states"])
    # matrices rebuilt in pure NumPy from the flat vector
    Ms = spec.state_dim
    C = np.zeros((Ms, Ms))
    a, _ = spec.layout["chol"]
    rows, cols = spec.chol_indices
    for k, (r, c) in enumerate(zip(rows, cols)):
        C[r, c] = p[a + k]
    lo, hi = spec.layout["delta"]
    delta = p[lo:hi]
    lo, hi = spec.layout["phi"]
    Phi = p[lo:hi].reshape(Ms, Ms)
    beta0, P0 = oracle.kalman_init(Phi, delta, C @ C.T)
    mean_err = np.abs(states.mean(axis=1) - beta0)
    sd = np.sqrt(np.diagonal(P0))
    assert np.all(mean_err < 4 * sd / np.sqrt(20000 / 20)), mean_err  # AR-adj
    cov = np.cov(states)
    np.testing.assert_allclose(cov, P0, rtol=0.2, atol=5e-4)
    # measurement noise: residual sd off the exact NumPy loadings
    gamma = p[spec.layout["gamma"][0]]
    Z = oracle.dns_loadings(gamma, np.asarray(MATS))
    obs_var = p[spec.layout["obs_var"][0]]
    resid = np.asarray(out["data"]) - Z @ states
    np.testing.assert_allclose(resid.std(), np.sqrt(obs_var), rtol=0.05)
    assert np.allclose(np.asarray(out["h"]), 0.0)  # no SV requested


def test_sv_inflates_measurement_variance(rng):
    """With SV on, residual variance is scaled by E[e^h] > 1 and the h path
    is a nontrivial AR(1); data stays finite."""
    spec, _ = yfm.create_model("1C", MATS, float_type="float64")
    p = jnp.asarray(stable_1c_params(spec, dtype=np.float64))
    out = yfm.simulate(spec, p, T=4000, key=jax.random.PRNGKey(1),
                       sv_phi=0.9, sv_sigma=0.4)
    h = np.asarray(out["h"])
    assert np.isfinite(np.asarray(out["data"])).all()
    assert h.std() > 0.3  # stationary sd = 0.4/sqrt(1-0.81) ≈ 0.92
    # lag-1 autocorrelation near φ_h
    ac = np.corrcoef(h[1:], h[:-1])[0, 1]
    assert 0.8 < ac < 0.97, ac


@pytest.mark.parametrize("code,point", [("1C", stable_1c_params),
                                        ("TVλ", stable_tvl_params)])
def test_simulated_panel_has_finite_loglik_at_truth(code, point, rng):
    """The filter must assign a finite loglik to the simulator's own output
    at the simulating parameters — generator and filter share one model."""
    spec, _ = yfm.create_model(code, MATS, float_type="float64")
    p = jnp.asarray(point(spec, dtype=np.float64)
                    if code == "1C" else point(spec))
    out = yfm.simulate(spec, p, T=120, key=jax.random.PRNGKey(2))
    ll = float(yfm.get_loss(spec, p, out["data"]))
    assert np.isfinite(ll), ll


def test_estimation_recovers_simulating_lambda(rng):
    """Round trip: single-start MLE on a simulated panel recovers λ within
    sampling error (the identifying parameter of the DNS loadings)."""
    from yieldfactormodels_jl_tpu.estimation import optimize as opt
    from yieldfactormodels_jl_tpu.models.loadings import dns_lambda

    spec, _ = yfm.create_model("1C", MATS, float_type="float64")
    p_true = stable_1c_params(spec, dtype=np.float64)
    out = yfm.simulate(spec, jnp.asarray(p_true), T=300,
                       key=jax.random.PRNGKey(3))
    start = p_true.copy()
    start[spec.layout["gamma"][0]] = np.log(0.8)  # start well off the truth
    _, ll, best, conv = opt.estimate(spec, np.asarray(out["data"]),
                                     start[:, None], max_iters=300)
    assert np.isfinite(ll)
    lam_hat = float(dns_lambda(jnp.asarray(best)[spec.layout["gamma"][0]]))
    assert abs(lam_hat - 0.5) < 0.05, lam_hat


def test_simulate_rejects_prediction_error_families():
    spec, _ = yfm.create_model("NS", MATS, float_type="float64")
    with pytest.raises(ValueError, match="generative"):
        yfm.simulate(spec, np.zeros(spec.n_params), T=10,
                     key=jax.random.PRNGKey(0))
