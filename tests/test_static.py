"""Static family + random-walk golden tests."""

import jax.numpy as jnp
import numpy as np

from tests import oracle
from yieldfactormodels_jl_tpu import create_model, get_loss, predict


def _static_params(spec):
    p = np.zeros(spec.n_params)
    p[0] = np.log(0.5)
    p[spec.L:spec.L + 3] = [0.3, -0.1, 0.05]
    Phi = np.array([[0.95, 0.02, 0.0], [0.01, 0.9, 0.03], [0.0, 0.02, 0.85]])
    p[spec.L + 3:] = Phi.T.reshape(-1)
    return p, Phi


def test_static_lambda_parity(maturities, yields_panel):
    spec, _ = create_model("NS", tuple(maturities), float_type="float64")
    assert spec.n_params == 13  # SURVEY.md §2.13
    p, Phi = _static_params(spec)
    Z = oracle.dns_loadings(p[0], maturities)
    want = oracle.static_filter(Z, p[1:4], Phi, yields_panel)
    res = predict(spec, jnp.asarray(p), jnp.asarray(yields_panel))
    np.testing.assert_allclose(np.asarray(res["preds"]), want, rtol=1e-9)
    want_loss = oracle.msed_loss_from_preds(want, yields_panel)
    got_loss = float(get_loss(spec, jnp.asarray(p), jnp.asarray(yields_panel)))
    np.testing.assert_allclose(got_loss, want_loss, rtol=1e-9)


def test_static_neural_param_count(maturities):
    spec, _ = create_model("NNS", tuple(maturities), float_type="float64")
    assert spec.n_params == 30  # 18 + 3 + 9 (SURVEY.md §2.13)


def test_random_walk_predicts_last_observation(maturities, yields_panel):
    spec, _ = create_model("RW", tuple(maturities), float_type="float64")
    p = np.zeros(spec.n_params)
    h = 4
    ext = np.concatenate([yields_panel, np.full((len(maturities), h), np.nan)], axis=1)
    res = predict(spec, jnp.asarray(p), jnp.asarray(ext))
    preds = np.asarray(res["preds"])
    # observed step t emits y_t; NaN steps keep emitting the last observation
    np.testing.assert_allclose(preds[:, 10], yields_panel[:, 10])
    for k in range(1, h + 1):
        np.testing.assert_allclose(preds[:, -k], yields_panel[:, -1])


def test_nan_forecast_extension_is_pure_transition(maturities, yields_panel):
    """forecasting.jl:141 trick: NaN columns ⇒ h-step-ahead forecasts."""
    spec, _ = create_model("NS", tuple(maturities), float_type="float64")
    p, Phi = _static_params(spec)
    h = 5
    ext = np.concatenate([yields_panel, np.full((len(maturities), h), np.nan)], axis=1)
    res = predict(spec, jnp.asarray(p), jnp.asarray(ext))
    # manual h-step transition: the last observed step already emits
    # ŷ = Z(μ + Φ·OLS(y_T)); each NaN step applies one more μ + Φβ
    Z = oracle.dns_loadings(p[0], maturities)
    delta = p[1:4]
    mu = (np.eye(3) - Phi) @ delta
    beta = mu + Phi @ oracle._ols(Z, yields_panel[:, -1])
    for k in range(h):
        beta = mu + Phi @ beta
        np.testing.assert_allclose(
            np.asarray(res["preds"][:, yields_panel.shape[1] + k]), Z @ beta, rtol=1e-9
        )


def _static_neural_params(spec, rng):
    p = np.zeros(spec.n_params)
    gamma = rng.standard_normal(18) / 10
    p[0:18] = gamma
    p[18:21] = [0.3, -0.1, 0.05]
    Phi = np.array([[0.95, 0.02, 0.0], [0.01, 0.9, 0.03], [0.0, 0.02, 0.85]])
    p[21:30] = Phi.T.reshape(-1)
    return p, gamma, Phi


def test_static_neural_parity(maturities, yields_panel):
    """NNS end-to-end golden parity (VERDICT round 1, item 4): fixed neural
    loadings built once from gamma (staticneural.jl:100-101), then the plain
    static OLS filter (models/filter.jl:93-110)."""
    spec, _ = create_model("NNS", tuple(maturities), float_type="float64")
    rng = np.random.default_rng(11)
    p, gamma, Phi = _static_neural_params(spec, rng)
    Z = oracle.neural_loadings(gamma, maturities, True)
    want = oracle.static_filter(Z, p[18:21], Phi, yields_panel)
    res = predict(spec, jnp.asarray(p), jnp.asarray(yields_panel))
    np.testing.assert_allclose(np.asarray(res["preds"]), want, rtol=1e-8)
    want_loss = oracle.msed_loss_from_preds(want, yields_panel)
    got_loss = float(get_loss(spec, jnp.asarray(p), jnp.asarray(yields_panel)))
    np.testing.assert_allclose(got_loss, want_loss, rtol=1e-8)


def test_static_neural_anchored_parity(maturities, yields_panel):
    """NNS-Anchored: same filter, no-detrend shape transforms."""
    spec, _ = create_model("NNS-Anchored", tuple(maturities), float_type="float64")
    rng = np.random.default_rng(12)
    p, gamma, Phi = _static_neural_params(spec, rng)
    Z = oracle.neural_loadings(gamma, maturities, False)
    want = oracle.static_filter(Z, p[18:21], Phi, yields_panel)
    res = predict(spec, jnp.asarray(p), jnp.asarray(yields_panel))
    np.testing.assert_allclose(np.asarray(res["preds"]), want, rtol=1e-8)
