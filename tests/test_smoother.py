"""RTS smoother (ops/smoother.py) vs an independent NumPy oracle."""

import jax.numpy as jnp
import numpy as np
import pytest

from yieldfactormodels_jl_tpu import create_model
from yieldfactormodels_jl_tpu.models.params import unpack_kalman
from yieldfactormodels_jl_tpu.ops import smoother

from tests import oracle
from tests.oracle import stable_1c_params


def _dns_case(maturities, yields_panel, with_nan=False):
    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    p = jnp.asarray(stable_1c_params(spec, dtype=np.float64))
    data = np.asarray(yields_panel[:, :40]).copy()
    if with_nan:
        data[:, 11] = np.nan
    return spec, p, data


@pytest.mark.parametrize("engine", ["joint", "univariate"])
@pytest.mark.parametrize("with_nan", [False, True])
def test_rts_matches_oracle(maturities, yields_panel, with_nan, engine):
    """Independent-NumPy-oracle parity (CLAUDE.md rule) for BOTH
    moment-emitting forward engines — incl. univariate_kf.filter_moments,
    whose beta_filt/P_filt are checked against the oracle's filtered
    moments, not just against the joint JAX path."""
    spec, p, data = _dns_case(maturities, yields_panel, with_nan)
    out = smoother.smooth(spec, p, jnp.asarray(data), engine=engine)
    kp = unpack_kalman(spec, p)
    Z = oracle.dns_loadings(float(kp.gamma[0]), np.asarray(maturities))
    bs, Ps, bf, Pf = oracle.rts_smoother(
        Z, np.asarray(kp.Phi), np.asarray(kp.delta),
        np.asarray(kp.Omega_state), float(kp.obs_var), data)
    np.testing.assert_allclose(np.asarray(out["beta_smooth"]).T, bs, rtol=1e-8,
                               atol=1e-10)
    np.testing.assert_allclose(np.asarray(out["P_smooth"]), Ps, rtol=1e-8,
                               atol=1e-12)
    np.testing.assert_allclose(np.asarray(out["beta_filt"]).T, bf, rtol=1e-8,
                               atol=1e-10)


@pytest.mark.parametrize("code", ["1C", "TVλ"])
def test_rts_univariate_engine_matches_joint(code, maturities, yields_panel):
    """engine='univariate' (Cholesky-free sequential-update moments) must
    produce the same smoothed moments as the joint form — identical algebra
    (Koopman–Durbin), f64 tight."""
    spec, _ = create_model(code, tuple(maturities), float_type="float64")
    if code == "1C":
        p = jnp.asarray(stable_1c_params(spec, dtype=np.float64))
    else:
        p = jnp.asarray(oracle.stable_tvl_params(spec))
    data = jnp.asarray(np.asarray(yields_panel[:, :30]))
    a = smoother.smooth(spec, p, data, engine="joint")
    b = smoother.smooth(spec, p, data, engine="univariate")
    for k in ("beta_smooth", "P_smooth", "beta_filt", "P_filt"):
        np.testing.assert_allclose(np.asarray(b[k]), np.asarray(a[k]),
                                   rtol=1e-8, atol=1e-11)


def test_rts_rejects_momentless_engines(maturities, yields_panel):
    """'sqrt'/'assoc' don't emit the RTS moment set: smooth must raise a
    clear error naming the limitation instead of silently switching engine —
    both via the explicit argument and via the process-wide config."""
    from yieldfactormodels_jl_tpu import config
    spec, p, data = _dns_case(maturities, yields_panel)
    with pytest.raises(ValueError, match="filtering-moments"):
        smoother.smooth(spec, p, jnp.asarray(data), engine="sqrt")
    prev = config.kalman_engine()
    config.set_kalman_engine("assoc")
    try:
        with pytest.raises(ValueError, match="filtering-moments"):
            smoother.smooth(spec, p, jnp.asarray(data))
    finally:
        config.set_kalman_engine(prev)


def test_rts_final_step_equals_filter_and_shrinks_variance(maturities, yields_panel):
    spec, p, data = _dns_case(maturities, yields_panel)
    out = smoother.smooth(spec, p, jnp.asarray(data))
    # β_{T−1|T} == β_{T−1|T−1} by construction
    np.testing.assert_allclose(np.asarray(out["beta_smooth"])[:, -1],
                               np.asarray(out["beta_filt"])[:, -1], rtol=1e-12)
    # smoothing never inflates uncertainty: tr(P_{t|T}) ≤ tr(P_{t|t}) + ulp
    tr_s = np.trace(np.asarray(out["P_smooth"]), axis1=1, axis2=2)
    tr_f = np.trace(np.asarray(out["P_filt"]), axis1=1, axis2=2)
    assert np.all(tr_s <= tr_f + 1e-12)


def test_rts_tvl_ekf_runs(maturities, yields_panel):
    """The backward pass is measurement-free, so the TVλ EKF smooths with the
    same code; pin shapes, finiteness, and the final-step identity."""
    spec, _ = create_model("TVλ", tuple(maturities), float_type="float64")
    p = oracle.stable_tvl_params(spec)
    data = jnp.asarray(yields_panel[:, :30])
    out = smoother.smooth(spec, jnp.asarray(p), data)
    assert np.asarray(out["beta_smooth"]).shape == (4, 30)
    assert np.isfinite(np.asarray(out["beta_smooth"])).all()
    assert np.isfinite(np.asarray(out["P_smooth"])).all()
    np.testing.assert_allclose(np.asarray(out["beta_smooth"])[:, -1],
                               np.asarray(out["beta_filt"])[:, -1], rtol=1e-12)


def test_rts_poisons_output_on_filter_failure(maturities, yields_panel):
    """A non-stationary Φ breaks the forward Cholesky (get_loss → −Inf); the
    smoother must return NaN moments, not finite garbage."""
    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    p = np.asarray(stable_1c_params(spec, dtype=np.float64))
    a, b = spec.layout["phi"]
    p[a:b] = np.diag([1.5, 1.5, 1.5]).reshape(-1)  # explosive transition
    from yieldfactormodels_jl_tpu import get_loss
    data = jnp.asarray(yields_panel[:, :30])
    assert float(get_loss(spec, jnp.asarray(p), data)) == -np.inf
    out = smoother.smooth(spec, jnp.asarray(p), data)
    assert np.isnan(np.asarray(out["beta_smooth"])).all()
    assert np.isnan(np.asarray(out["P_smooth"])).all()


def test_rts_rejects_non_kalman(maturities):
    spec, _ = create_model("NS", tuple(maturities), float_type="float64")
    with pytest.raises(ValueError, match="Kalman"):
        smoother.smooth(spec, jnp.zeros(spec.n_params), jnp.zeros((len(maturities), 5)))
