"""Guard the naive reference-equivalent measurement harness against rot.

benchmarks/naive_ref.py produces the second ratio column of BASELINE.md's
dual-ratio table; these smokes keep it importable/runnable and pin the one
checkable numeric property: with sv_sigma -> 0 the naive NumPy particle
filter collapses to the exact Kalman log-likelihood (the same collapse
tests/test_extensions.py pins for the jitted PF).
"""

import os
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
for p in (os.path.join(ROOT, "benchmarks"), ROOT):
    if p not in sys.path:
        sys.path.insert(0, p)

import common  # noqa: E402
import naive_ref  # noqa: E402


def test_units_and_tiny_configs_run():
    w, d = naive_ref.unit_afns5_pass()
    assert w > 0 and "passes" in d
    w, d = naive_ref.naive_bootstrap(n_resamples=3, n_lambdas=2)
    assert w > 0
    w, d = naive_ref.naive_afns5_sv_pf(n_draws=1, n_particles=20)
    assert w > 0 and "finite 1/1" in d
    # the BENCH_SCEN dual-ratio denominator stays runnable at a tiny lattice
    w, d = naive_ref.naive_scenario_fan(R=2, G=2, D=1, Pn=8, S=2, h=2,
                                        n_paths=2)
    assert w > 0 and "fan" in d
    # the BENCH_LONGT TVλ dual-ratio denominator (iterated-SLR naive loop)
    w, d = naive_ref.unit_slr_pass(T=200, sweeps=2, chunk=64)
    assert w > 0 and "sweeps" in d
    # the load-fan-bench denominator: per-update full-fan recomputes
    w, d = naive_ref.unit_fan(subs=2, S=2, h=2)
    assert w > 0 and "fan" in d


def test_naive_pf_collapses_to_kalman_loglik():
    """sv_sigma = 0 (and h0 = 0) makes every particle identical, so the
    naive PF loglik must equal the exact Kalman loglik of the same draw."""
    import oracle  # tests/oracle.py (sys.path has tests/ under pytest)
    from yieldfactormodels_jl_tpu import create_model

    spec, _ = create_model("AFNS5", tuple(common.MATURITIES),
                           float_type="float32")
    data = np.asarray(common.afns5_panel(), dtype=np.float64)[:, :40]
    p = common.afns5_params(spec)
    (tt,) = naive_ref._afns5_tensors(spec, [p])
    Z, d, Phi, delta, cholOm, beta0, S0, obs_var = tt
    rng = np.random.default_rng(0)
    got = naive_ref._naive_pf_one_draw(
        rng, Z, d, Phi, delta, cholOm, beta0, S0, float(obs_var), data,
        Pn=8, sv_phi=0.9, sv_sigma=0.0)
    # exact Kalman loglik: the oracle loop shares the PF's conventions
    # (columns 0..T-2 processed, first innovation skipped); rtol absorbs the
    # PF init's 1e-9 PSD jitter on P0
    want = oracle.kalman_filter_loglik(
        Z, Phi, delta, cholOm @ cholOm.T, float(obs_var),
        data - d[:, None])
    np.testing.assert_allclose(got, want, rtol=1e-5)
