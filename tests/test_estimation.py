"""Estimation-layer tests: optimizers, multi-start, block-coordinate, grids."""

import jax.numpy as jnp
import numpy as np

from yieldfactormodels_jl_tpu import create_model, get_loss, transform_params
from yieldfactormodels_jl_tpu.estimation import optimize as opt
from yieldfactormodels_jl_tpu.estimation.neldermead import nelder_mead


def test_neldermead_on_rosenbrock():
    def rosen(x):
        return jnp.sum(100.0 * (x[1:] - x[:-1] ** 2) ** 2 + (1 - x[:-1]) ** 2)

    x, f, it = nelder_mead(rosen, jnp.zeros(2), max_iters=2000, f_tol=1e-14)
    np.testing.assert_allclose(np.asarray(x), [1.0, 1.0], atol=2e-3)


def _static_truth(spec):
    p = np.zeros(spec.n_params)
    p[0] = np.log(0.5)
    p[1:4] = [0.3, -0.1, 0.05]
    Phi = np.diag([0.95, 0.9, 0.85])
    p[4:13] = Phi.T.reshape(-1)
    return p


def test_estimate_improves_loglik(maturities, yields_panel):
    spec, _ = create_model("NS", tuple(maturities), float_type="float64")
    truth = _static_truth(spec)
    start = truth.copy()
    start[0] += 0.3  # perturb λ driver
    start[1:4] += 0.05
    ll_start = float(get_loss(spec, jnp.asarray(start), jnp.asarray(yields_panel)))
    init, ll, best, _ = opt.estimate(
        spec, yields_panel, start[:, None], max_iters=200
    )
    assert ll > ll_start
    ll_check = float(get_loss(spec, jnp.asarray(best), jnp.asarray(yields_panel)))
    np.testing.assert_allclose(ll_check, ll, rtol=1e-6)


def test_multistart_vmapped_picks_best(maturities, yields_panel):
    spec, _ = create_model("NS", tuple(maturities), float_type="float64")
    truth = _static_truth(spec)
    starts = np.stack([truth + 0.0, truth + 0.2, truth - 0.2], axis=1)  # (P, 3)
    _, ll_multi, best, _ = opt.estimate(spec, yields_panel, starts, max_iters=100)
    _, ll_single, _, _ = opt.estimate(spec, yields_panel, starts[:, 1:2], max_iters=100)
    assert ll_multi >= ll_single - 1e-9


def test_estimate_steps_block_coordinate(maturities, yields_panel):
    spec, _ = create_model("SD-NS", tuple(maturities), float_type="float64")
    vals = [1e-3, 0.97, np.log(0.5), 0.3, -0.1, 0.05]
    Phi = np.diag([0.95, 0.9, 0.85])
    p = np.asarray(vals + list(Phi.T.reshape(-1)))
    groups = ["1"] * 3 + ["2"] * 12
    table = {  # shrunk iteration budgets to keep the test fast
        "1": ("neldermead", dict(max_iters=60)),
        "2": ("lbfgs", dict(max_iters=30, g_tol=1e-6, f_abstol=1e-6)),
    }
    ll_start = float(get_loss(spec, jnp.asarray(p), jnp.asarray(yields_panel)))
    init, ll, best, _ = opt.estimate_steps(
        spec, yields_panel, p[:, None], groups, max_group_iters=2,
        optimizers=table,
    )
    assert np.isfinite(ll)
    assert ll >= ll_start - 1e-9
    assert best.shape == p.shape


def test_try_initializations_msed_grid(maturities, yields_panel):
    spec, _ = create_model("SD-NS", tuple(maturities), float_type="float64")
    vals = [1e-3, 0.97, np.log(0.5), 0.3, -0.1, 0.05]
    Phi = np.diag([0.95, 0.9, 0.85])
    p = np.asarray(vals + list(Phi.T.reshape(-1)))
    out = opt.try_initializations(spec, p, jnp.asarray(yields_panel))
    assert out.shape == (15, 1)
    # the winner must be at least as good as the input
    ll_in = float(get_loss(spec, jnp.asarray(p), jnp.asarray(yields_panel)))
    ll_out = float(get_loss(spec, jnp.asarray(out[:, 0]), jnp.asarray(yields_panel)))
    assert ll_out >= ll_in - 1e-12


def test_try_initializations_static_jitter(maturities, yields_panel):
    spec, _ = create_model("NS", tuple(maturities), float_type="float64")
    p = _static_truth(spec)
    out = opt.try_initializations(spec, p, jnp.asarray(yields_panel), max_tries=3)
    assert out.shape == (13, 4)
    np.testing.assert_allclose(out[:, 0], p)
    # jitters only touch the non-(δ,Φ) head
    np.testing.assert_allclose(out[1:, 1][3:], p[4:])


def test_estimate_windows_batched(maturities, yields_panel):
    spec, _ = create_model("NS", tuple(maturities), float_type="float64")
    truth = _static_truth(spec)
    from yieldfactormodels_jl_tpu.models.params import untransform_params

    raw = np.asarray(untransform_params(spec, jnp.asarray(truth)))
    starts = np.stack([raw, raw + 0.1], axis=0)  # (S=2, P)
    w_starts = np.array([0, 0, 10])
    w_ends = np.array([50, 60, 70])
    xs, lls = opt.estimate_windows(
        spec, yields_panel, starts, w_starts, w_ends, max_iters=40
    )
    assert xs.shape == (3, 2, 13)
    assert lls.shape == (3, 2)
    assert np.all(np.isfinite(np.asarray(lls)))
    # batched window loss equals the truncated-sample loss at the same params
    from yieldfactormodels_jl_tpu.models import static_model as SM

    p0 = transform_params(spec, jnp.asarray(np.asarray(xs)[2, 0]))
    l_mask = float(SM.get_loss(spec, p0, jnp.asarray(yields_panel), start=10, end=70))
    l_trunc = float(SM.get_loss(spec, p0, jnp.asarray(yields_panel[:, 10:70])))
    np.testing.assert_allclose(l_mask, l_trunc, rtol=1e-9)


def test_estimate_steps_raises_on_structurally_broken_objective(maturities):
    """Overflow-scale data makes every loglik eval −Inf (v² overflows) ⇒ the
    objective is the penalty everywhere; the reference rethrows errors on the
    first group iteration (optimization.jl:244-250) — here that surfaces as a
    RuntimeError, not a silent penalty 'optimum'."""
    import pytest

    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    data = np.full((len(maturities), 30), 1e200)
    starts = np.full((spec.n_params, 1), 0.5)
    groups = ["1"] * spec.n_params
    with pytest.raises(RuntimeError, match="structurally incompatible"):
        opt.estimate_steps(spec, data, starts, groups, max_group_iters=1)


def test_estimate_steps_reports_real_convergence(maturities, yields_panel):
    spec, _ = create_model("NS", tuple(maturities), float_type="float64")
    truth = _static_truth(spec)
    groups = ["1"] * 4 + ["2"] * 9  # non-(δ,Φ) / (δ,Φ) split
    _, ll, _, conv = opt.estimate_steps(
        spec, yields_panel, truth[:, None], groups, max_group_iters=6)
    assert isinstance(conv, opt.Convergence)
    assert np.isfinite(ll)
    assert 1 <= conv.iterations <= 6
