"""Estimation-layer tests: optimizers, multi-start, block-coordinate, grids."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from yieldfactormodels_jl_tpu import create_model, get_loss, transform_params
from yieldfactormodels_jl_tpu.estimation import optimize as opt
from yieldfactormodels_jl_tpu.estimation.neldermead import nelder_mead


def test_neldermead_on_rosenbrock():
    def rosen(x):
        return jnp.sum(100.0 * (x[1:] - x[:-1] ** 2) ** 2 + (1 - x[:-1]) ** 2)

    x, f, it = nelder_mead(rosen, jnp.zeros(2), max_iters=2000, f_tol=1e-14)
    np.testing.assert_allclose(np.asarray(x), [1.0, 1.0], atol=2e-3)


def _static_truth(spec):
    p = np.zeros(spec.n_params)
    p[0] = np.log(0.5)
    p[1:4] = [0.3, -0.1, 0.05]
    Phi = np.diag([0.95, 0.9, 0.85])
    p[4:13] = Phi.T.reshape(-1)
    return p


def test_estimate_improves_loglik(maturities, yields_panel):
    spec, _ = create_model("NS", tuple(maturities), float_type="float64")
    truth = _static_truth(spec)
    start = truth.copy()
    start[0] += 0.3  # perturb λ driver
    start[1:4] += 0.05
    ll_start = float(get_loss(spec, jnp.asarray(start), jnp.asarray(yields_panel)))
    init, ll, best, _ = opt.estimate(
        spec, yields_panel, start[:, None], max_iters=200
    )
    assert ll > ll_start
    ll_check = float(get_loss(spec, jnp.asarray(best), jnp.asarray(yields_panel)))
    np.testing.assert_allclose(ll_check, ll, rtol=1e-6)


def test_multistart_vmapped_picks_best(maturities, yields_panel):
    spec, _ = create_model("NS", tuple(maturities), float_type="float64")
    truth = _static_truth(spec)
    starts = np.stack([truth + 0.0, truth + 0.2, truth - 0.2], axis=1)  # (P, 3)
    _, ll_multi, best, _ = opt.estimate(spec, yields_panel, starts, max_iters=100)
    _, ll_single, _, _ = opt.estimate(spec, yields_panel, starts[:, 1:2], max_iters=100)
    assert ll_multi >= ll_single - 1e-9


def test_estimate_steps_block_coordinate(maturities, yields_panel):
    spec, _ = create_model("SD-NS", tuple(maturities), float_type="float64")
    vals = [1e-3, 0.97, np.log(0.5), 0.3, -0.1, 0.05]
    Phi = np.diag([0.95, 0.9, 0.85])
    p = np.asarray(vals + list(Phi.T.reshape(-1)))
    groups = ["1"] * 3 + ["2"] * 12
    table = {  # shrunk iteration budgets to keep the test fast
        "1": ("neldermead", dict(max_iters=60)),
        "2": ("lbfgs", dict(max_iters=30, g_tol=1e-6, f_abstol=1e-6)),
    }
    ll_start = float(get_loss(spec, jnp.asarray(p), jnp.asarray(yields_panel)))
    init, ll, best, _ = opt.estimate_steps(
        spec, yields_panel, p[:, None], groups, max_group_iters=2,
        optimizers=table,
    )
    assert np.isfinite(ll)
    assert ll >= ll_start - 1e-9
    assert best.shape == p.shape


def test_try_initializations_msed_grid(maturities, yields_panel):
    spec, _ = create_model("SD-NS", tuple(maturities), float_type="float64")
    vals = [1e-3, 0.97, np.log(0.5), 0.3, -0.1, 0.05]
    Phi = np.diag([0.95, 0.9, 0.85])
    p = np.asarray(vals + list(Phi.T.reshape(-1)))
    out = opt.try_initializations(spec, p, jnp.asarray(yields_panel))
    assert out.shape == (15, 1)
    # the winner must be at least as good as the input
    ll_in = float(get_loss(spec, jnp.asarray(p), jnp.asarray(yields_panel)))
    ll_out = float(get_loss(spec, jnp.asarray(out[:, 0]), jnp.asarray(yields_panel)))
    assert ll_out >= ll_in - 1e-12


def test_try_initializations_static_jitter(maturities, yields_panel):
    spec, _ = create_model("NS", tuple(maturities), float_type="float64")
    p = _static_truth(spec)
    out = opt.try_initializations(spec, p, jnp.asarray(yields_panel), max_tries=3)
    assert out.shape == (13, 4)
    np.testing.assert_allclose(out[:, 0], p)
    # jitters only touch the non-(δ,Φ) head
    np.testing.assert_allclose(out[1:, 1][3:], p[4:])


def test_estimate_windows_batched(maturities, yields_panel):
    spec, _ = create_model("NS", tuple(maturities), float_type="float64")
    truth = _static_truth(spec)
    from yieldfactormodels_jl_tpu.models.params import untransform_params

    raw = np.asarray(untransform_params(spec, jnp.asarray(truth)))
    starts = np.stack([raw, raw + 0.1], axis=0)  # (S=2, P)
    w_starts = np.array([0, 0, 10])
    w_ends = np.array([50, 60, 70])
    xs, lls = opt.estimate_windows(
        spec, yields_panel, starts, w_starts, w_ends, max_iters=40
    )
    assert xs.shape == (3, 2, 13)
    assert lls.shape == (3, 2)
    assert np.all(np.isfinite(np.asarray(lls)))
    # batched window loss equals the truncated-sample loss at the same params
    from yieldfactormodels_jl_tpu.models import static_model as SM

    p0 = transform_params(spec, jnp.asarray(np.asarray(xs)[2, 0]))
    l_mask = float(SM.get_loss(spec, p0, jnp.asarray(yields_panel), start=10, end=70))
    l_trunc = float(SM.get_loss(spec, p0, jnp.asarray(yields_panel[:, 10:70])))
    np.testing.assert_allclose(l_mask, l_trunc, rtol=1e-9)


def test_estimate_steps_raises_on_structurally_broken_objective(maturities):
    """Overflow-scale data makes every loglik eval −Inf (v² overflows) ⇒ the
    objective is the penalty everywhere; the reference rethrows errors on the
    first group iteration (optimization.jl:244-250) — here that surfaces as a
    RuntimeError, not a silent penalty 'optimum'."""
    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    data = np.full((len(maturities), 30), 1e200)
    starts = np.full((spec.n_params, 1), 0.5)
    groups = ["1"] * spec.n_params
    with pytest.raises(RuntimeError, match="structurally incompatible"):
        opt.estimate_steps(spec, data, starts, groups, max_group_iters=1)


def test_estimate_steps_reports_real_convergence(maturities, yields_panel):
    spec, _ = create_model("NS", tuple(maturities), float_type="float64")
    truth = _static_truth(spec)
    groups = ["1"] * 4 + ["2"] * 9  # non-(δ,Φ) / (δ,Φ) split
    _, ll, _, conv = opt.estimate_steps(
        spec, yields_panel, truth[:, None], groups, max_group_iters=6)
    assert isinstance(conv, opt.Convergence)
    assert np.isfinite(ll)
    assert 1 <= conv.iterations <= 6


def test_fused_check_defaults_to_fallback(monkeypatch):
    """The trust-but-verify guard must DEFAULT to fallback while the Pallas
    adjoints' on-chip grad gates are unpassed (VERDICT round 3, weak #2: the
    round-3 device window recorded an unresolved fused-path optimum
    regression, BASELINE.md 'Anomaly under investigation').  Flipping back to
    warn-only requires hw_verify grad-gate evidence, not a silent edit."""
    monkeypatch.delenv("YFM_FUSED_CHECK", raising=False)
    assert opt._fused_check_mode() == "fallback"
    monkeypatch.setenv("YFM_FUSED_CHECK", "warn")
    assert opt._fused_check_mode() == "warn"


def _sd_point(spec, rng):
    from tests.oracle import generic_stable_params

    return generic_stable_params(spec, rng)


@pytest.mark.parametrize("code", ["SD-NS", "NS"])
def test_msed_closed_form_group2_is_block_optimal(code, maturities,
                                                  yields_panel, rng):
    """The closed-form (δ, Φ) solve lands on a stationary point of the FULL
    loss restricted to the block: on a fully-observed window the γ trajectory
    and the per-step OLS β̄ never depend on (δ, Φ) (score_driven._step; same
    structure with constant Z for the static families, static_model), so
    the sub-objective is exactly quadratic and one 12×12 solve is its global
    optimum — the redesign of the reference's group-"2" L-BFGS
    (optimization.jl:439-494) that removes config 6's per-pass latency wall."""
    import jax

    from yieldfactormodels_jl_tpu.models.params import untransform_params

    spec, _ = create_model(code, tuple(maturities), float_type="float64")
    T = yields_panel.shape[1]
    data = jnp.asarray(yields_panel)
    cons = _sd_point(spec, rng)
    lo_d, _ = spec.layout["delta"]
    _, hi_p = spec.layout["phi"]
    cons[lo_d:hi_p] *= 0.8  # push the block off its optimum (diag stays <1)
    raw = np.asarray(untransform_params(spec, jnp.asarray(cons)))
    inds = tuple(range(lo_d, hi_p))
    assert opt._msed_closed_applicable(spec, inds, data, 0, T)

    runner = opt._jitted_group_opt_msed_closed(spec, T)
    X_new, f = runner(jnp.asarray(raw)[None, :], data,
                      jnp.asarray(0), jnp.asarray(T))
    f_old = float(opt._finite_objective(spec, data, jnp.asarray(raw), 0, T))
    assert float(f[0]) < f_old  # improved, and f is the accepted value

    idx = jnp.asarray(inds)
    x_new = jnp.asarray(X_new)[0]

    def sub(x):
        return opt._finite_objective(spec, data, x_new.at[idx].set(x), 0, T)

    g_new = np.asarray(jax.grad(sub)(x_new[idx]))
    g_old = np.asarray(jax.grad(sub)(jnp.asarray(raw)[idx]))
    assert np.linalg.norm(g_new) < 1e-6 * max(1.0, np.linalg.norm(g_old))


def test_msed_closed_form_gates_on_missing_data(maturities, yields_panel):
    """A NaN inside the window breaks exact quadraticity (β carries through Φ
    across the gap) — the gate must refuse; a NaN beyond ``end`` is fine."""
    spec, _ = create_model("SD-NS", tuple(maturities), float_type="float64")
    lo_d, _ = spec.layout["delta"]
    _, hi_p = spec.layout["phi"]
    inds = tuple(range(lo_d, hi_p))
    T = yields_panel.shape[1]
    holed = np.array(yields_panel)
    holed[0, T // 2] = np.nan
    assert not opt._msed_closed_applicable(spec, inds, holed, 0, T)
    assert opt._msed_closed_applicable(spec, inds, holed, 0, T // 2)
    # wrong block or an unsupported family (random walk): refuse
    assert not opt._msed_closed_applicable(spec, inds[1:], yields_panel, 0, T)
    rspec, _ = create_model("RW", tuple(maturities), float_type="float64")
    r_inds = tuple(range(rspec.layout["delta"][0], rspec.layout["phi"][1]))
    assert not opt._msed_closed_applicable(rspec, r_inds, yields_panel, 0, T)


def test_estimate_steps_closed_form_beats_lbfgs_path(maturities, yields_panel,
                                                     monkeypatch, rng):
    """estimate_steps with the closed-form group-2 runner reaches at least the
    LL of the pure-iterative path on the same starts (accept-if-improved can
    only help), at a fraction of the filter passes."""
    spec, _ = create_model("SD-NS", tuple(maturities), float_type="float64")
    groups = list(spec.default_param_groups())
    start_p = _sd_point(spec, np.random.default_rng(7))[:, None]

    monkeypatch.setenv("YFM_MSED_CLOSED", "0")
    _, ll_iter, _, _ = opt.estimate_steps(spec, yields_panel, start_p, groups,
                                          max_group_iters=3)
    monkeypatch.delenv("YFM_MSED_CLOSED")
    _, ll_closed, _, _ = opt.estimate_steps(spec, yields_panel, start_p, groups,
                                            max_group_iters=3)
    assert np.isfinite(ll_closed)
    assert ll_closed >= ll_iter - 1e-6


def test_msed_closed_form_matches_numpy_oracle(maturities, yields_panel, rng):
    """CLAUDE.md parity rule: the closed-form solve must agree with an
    INDEPENDENT NumPy float64 computation (oracle filter loop + lstsq normal
    equations), never only with another JAX path — a systematic bug shared by
    scan_filter's trajectory outputs and the design-matrix assembly would
    cancel in the JAX-vs-JAX tests."""
    from tests import oracle
    from yieldfactormodels_jl_tpu.models.params import (transform_params,
                                                        untransform_params)

    spec, _ = create_model("SD-NS", tuple(maturities), float_type="float64")
    cons = _sd_point(spec, rng)
    lo_d, hi_d = spec.layout["delta"]
    lo_p, hi_p = spec.layout["phi"]
    cons[lo_d:hi_p] *= 0.8
    raw = jnp.asarray(np.asarray(untransform_params(spec, jnp.asarray(cons))))

    T = yields_panel.shape[1]
    runner = opt._jitted_group_opt_msed_closed(spec, T)
    X_new, _ = runner(raw[None, :], jnp.asarray(yields_panel),
                      jnp.asarray(0), jnp.asarray(T))
    got = np.asarray(transform_params(spec, jnp.asarray(X_new)[0]))

    struct = {"A": cons[0:1], "B": cons[1:2], "omega": cons[2:3],
              "delta": cons[lo_d:hi_d],
              "Phi": cons[lo_p:hi_p].reshape(3, 3).T}
    want_delta, want_Phi = oracle.msed_lambda_closed_delta_phi(
        struct, maturities, yields_panel)
    np.testing.assert_allclose(got[lo_d:hi_d], want_delta, rtol=1e-6)
    np.testing.assert_allclose(got[lo_p:hi_p].reshape(3, 3).T, want_Phi,
                               rtol=1e-6, atol=1e-8)


def test_static_closed_form_matches_numpy_oracle(maturities, yields_panel):
    """Static-branch twin of the MSED oracle parity check: the constant-Z
    closed-form solve must agree with an independent NumPy float64
    computation (oracle per-column OLS loop + lstsq), never only with
    another JAX path (CLAUDE.md parity rule)."""
    from tests import oracle
    from yieldfactormodels_jl_tpu.models.params import (transform_params,
                                                        untransform_params)

    spec, _ = create_model("NS", tuple(maturities), float_type="float64")
    cons = np.asarray(oracle.stable_ns_params(spec, dtype=np.float64))
    lo_d, hi_d = spec.layout["delta"]
    lo_p, hi_p = spec.layout["phi"]
    cons[lo_d:hi_p] *= 0.8
    raw = jnp.asarray(np.asarray(untransform_params(spec, jnp.asarray(cons))))

    T = yields_panel.shape[1]
    runner = opt._jitted_group_opt_msed_closed(spec, T)
    X_new, _ = runner(raw[None, :], jnp.asarray(yields_panel),
                      jnp.asarray(0), jnp.asarray(T))
    got = np.asarray(transform_params(spec, jnp.asarray(X_new)[0]))

    Z = np.asarray(oracle.dns_loadings(float(cons[spec.layout["gamma"][0]]),
                                       maturities))
    want_delta, want_Phi = oracle.static_closed_delta_phi(Z, yields_panel)
    np.testing.assert_allclose(got[lo_d:hi_d], want_delta, rtol=1e-6)
    np.testing.assert_allclose(got[lo_p:hi_p].reshape(3, 3).T, want_Phi,
                               rtol=1e-6, atol=1e-8)


def test_closed_form_survives_nan_forecast_tail(maturities, yields_panel, rng):
    """Regression: NaN data OUTSIDE the window (forecast tails) must not
    poison the normal equations through 0·NaN masking — the solve must still
    improve the block, not silently no-op (review finding, round 4)."""
    from yieldfactormodels_jl_tpu.models.params import untransform_params

    spec, _ = create_model("SD-NS", tuple(maturities), float_type="float64")
    cons = _sd_point(spec, rng)
    lo_d, _ = spec.layout["delta"]
    _, hi_p = spec.layout["phi"]
    cons[lo_d:hi_p] *= 0.8
    raw = jnp.asarray(np.asarray(untransform_params(spec, jnp.asarray(cons))))

    T_obs = yields_panel.shape[1]
    ext = np.concatenate([yields_panel,
                          np.full((yields_panel.shape[0], 12), np.nan)], 1)
    assert opt._msed_closed_applicable(
        spec, tuple(range(lo_d, hi_p)), ext, 0, T_obs)
    runner = opt._jitted_group_opt_msed_closed(spec, ext.shape[1])
    X_new, f = runner(raw[None, :], jnp.asarray(ext),
                      jnp.asarray(0), jnp.asarray(T_obs))
    f_old = float(opt._finite_objective(spec, jnp.asarray(ext), raw,
                                        0, T_obs))
    assert float(f[0]) < f_old  # improved — i.e. the candidate was taken
    assert not np.allclose(np.asarray(X_new)[0], np.asarray(raw))


def test_estimate_steps_ssd_guard_falls_back_on_kernel_disagreement(
        maturities, yields_panel, monkeypatch, rng):
    """estimate_steps' kernel-valued convergence path gets the same
    trust-but-verify contract as estimate(): a corrupted SSD kernel value
    must be caught by the one scan-engine eval of the winner and, under the
    fallback default, the whole estimation re-runs on the scan engine."""
    spec, _ = create_model("SD-NS", tuple(maturities), float_type="float64")
    start_p = _sd_point(spec, rng)[:, None]
    groups = list(spec.default_param_groups())
    table = {"1": ("neldermead", dict(max_iters=20)),
             "2": ("lbfgs", dict(max_iters=10, g_tol=1e-6, f_abstol=1e-6))}

    monkeypatch.setenv("YFM_SSD_PALLAS", "force")
    real = opt._jitted_ssd_batch_loss

    def corrupted(spec_, T_):
        fn = real(spec_, T_)
        return lambda p, d, s, e: fn(p, d, s, e) + 0.1  # systematic fault

    monkeypatch.setattr(opt, "_jitted_ssd_batch_loss", corrupted)
    _, ll, best, _ = opt.estimate_steps(spec, yields_panel, start_p, groups,
                                        max_group_iters=1, optimizers=table)
    # the fallback re-ran on the scan engine: the reported ll is consistent
    # with an independent scan-engine eval of the returned params
    ll_check = float(get_loss(spec, jnp.asarray(best),
                              jnp.asarray(yields_panel)))
    np.testing.assert_allclose(ll, ll_check, rtol=1e-9)
    # the fallback threads _force_scan as a call argument — the knob itself
    # is untouched (no process-global env mutation)
    assert os.environ["YFM_SSD_PALLAS"] == "force"



def test_neural_closed_form_matches_numpy_oracle(maturities, yields_panel):
    """Flagship-path parity (CLAUDE.md rule): the closed-form (δ, Φ) solve
    for 1SSD-NNS — the exact model the config-6 device race runs — must
    agree with the independent NumPy oracle (per-step FD-score filter loop +
    lstsq normal equations).  The oracle's finite-difference inner score
    tracks the library's AD score to ~1e-6 (test_score_driven parity), so
    the solved block matches to the same order."""
    from tests import oracle
    from yieldfactormodels_jl_tpu.models.params import (transform_params,
                                                        untransform_params)

    spec, _ = create_model("1SSD-NNS", tuple(maturities), float_type="float64")
    cons = _sd_point(spec, np.random.default_rng(5))
    lo_d, hi_d = spec.layout["delta"]
    lo_p, hi_p = spec.layout["phi"]
    cons[lo_d:hi_p] *= 0.8
    raw = jnp.asarray(np.asarray(untransform_params(spec, jnp.asarray(cons))))

    T = yields_panel.shape[1]
    runner = opt._jitted_group_opt_msed_closed(spec, T)
    X_new, _ = runner(raw[None, :], jnp.asarray(yields_panel),
                      jnp.asarray(0), jnp.asarray(T))
    got = np.asarray(transform_params(spec, jnp.asarray(X_new)[0]))

    struct = oracle.neural_struct_from_flat(cons)
    _, traj = oracle.msed_neural_filter(
        struct, maturities, yields_panel, transform_bool=True,
        scale_grad=True, forget_factor=spec.forget_factor, record_traj=True)
    want_delta, want_Phi = oracle.closed_delta_phi_from_traj(traj,
                                                             yields_panel)
    np.testing.assert_allclose(got[lo_d:hi_d], want_delta, rtol=2e-5)
    np.testing.assert_allclose(got[lo_p:hi_p].reshape(3, 3).T, want_Phi,
                               rtol=2e-5, atol=1e-7)


def test_closed_form_mixed_lanes_are_independent(maturities, yields_panel, rng):
    """vmap edge: a garbage lane (non-stationary Φ ⇒ penalty objective) must
    not perturb a healthy lane's closed-form solution, and must itself come
    back unchanged (accept-guard refuses per lane)."""
    from yieldfactormodels_jl_tpu.models.params import untransform_params

    spec, _ = create_model("SD-NS", tuple(maturities), float_type="float64")
    cons = _sd_point(spec, rng)
    lo_d, _ = spec.layout["delta"]
    _, hi_p = spec.layout["phi"]
    cons[lo_d:hi_p] *= 0.8
    raw_ok = np.asarray(untransform_params(spec, jnp.asarray(cons)))
    raw_bad = raw_ok.copy()
    raw_bad[hi_p - 9:hi_p] = 50.0  # Φ far outside stationarity in raw space

    T = yields_panel.shape[1]
    runner = opt._jitted_group_opt_msed_closed(spec, T)
    X2, f2 = runner(jnp.asarray(np.stack([raw_ok, raw_bad])),
                    jnp.asarray(yields_panel), jnp.asarray(0), jnp.asarray(T))
    X1, f1 = runner(jnp.asarray(raw_ok)[None], jnp.asarray(yields_panel),
                    jnp.asarray(0), jnp.asarray(T))
    # healthy lane identical whether or not a garbage lane rides along
    np.testing.assert_allclose(np.asarray(X2)[0], np.asarray(X1)[0],
                               rtol=1e-12)
    np.testing.assert_allclose(float(f2[0]), float(f1[0]), rtol=1e-12)
