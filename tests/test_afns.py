"""AFNS3/AFNS5 tests: loadings, yield adjustment, Kalman integration."""

import jax.numpy as jnp
import numpy as np

from tests import oracle
from yieldfactormodels_jl_tpu import create_model, get_loss, predict
from yieldfactormodels_jl_tpu.models.afns import (
    afns_lambdas, afns_loadings, yield_adjustment
)


def _afns5_params(spec):
    """[γ(2), σ², chol(15), δ(5), Φ(25)] = 48."""
    assert spec.n_params == 48
    p = np.zeros(48)
    p[0] = np.log(0.5)
    p[1] = np.log(0.15)
    p[2] = 4e-4
    k = 3
    C = np.zeros((5, 5))
    for j in range(5):
        for i in range(j + 1):
            v = 0.05 + 0.01 * i if i == j else 0.002
            C[i, j] = v
            p[k] = v
            k += 1
    p[18:23] = [4.0, -1.0, 0.5, -0.3, 0.2]
    Phi = np.diag([0.98, 0.94, 0.9, 0.92, 0.88])
    p[23:48] = Phi.reshape(-1)
    return p, C.T @ C, Phi


def test_afns5_loadings_structure(maturities):
    gamma = jnp.asarray([np.log(0.5), np.log(0.15)])
    Z = np.asarray(afns_loadings(gamma, jnp.asarray(maturities), 5))
    assert Z.shape == (len(maturities), 5)
    np.testing.assert_allclose(Z[:, 0], 1.0)
    lam1, lam2 = np.asarray(afns_lambdas(gamma))
    for col, lam in ((1, lam1), (3, lam2)):
        tau = lam * maturities
        np.testing.assert_allclose(Z[:, col], (1 - np.exp(-tau)) / tau, rtol=1e-7)
        np.testing.assert_allclose(Z[:, col + 1], Z[:, col] - np.exp(-tau), rtol=1e-6)


def test_yield_adjustment_against_dense_quadrature(maturities):
    """Quadrature result converges: 64-point grid ≈ 2048-point grid."""
    gamma = jnp.asarray([np.log(0.5), np.log(0.15)])
    Omega = np.diag([0.01, 0.02, 0.03, 0.015, 0.025])
    a64 = np.asarray(yield_adjustment(gamma, jnp.asarray(Omega),
                                      jnp.asarray(maturities), 5, quad_points=64))
    a2k = np.asarray(yield_adjustment(gamma, jnp.asarray(Omega),
                                      jnp.asarray(maturities), 5, quad_points=2048))
    np.testing.assert_allclose(a64, a2k, rtol=2e-3, atol=1e-9)
    assert np.all(a64 <= 0)  # positive-semidefinite Ω ⇒ non-positive adjustment
    # level-only Ω has closed form: α(τ) = −σ²τ²/6
    Ol = np.zeros((5, 5)); Ol[0, 0] = 0.01
    al = np.asarray(yield_adjustment(gamma, jnp.asarray(Ol),
                                     jnp.asarray(maturities), 5, quad_points=512))
    np.testing.assert_allclose(al, -0.01 * maturities ** 2 / 6, rtol=1e-5)


def test_afns5_kalman_loglik_matches_oracle(maturities, yields_panel):
    spec, canon = create_model("AFNS5", tuple(maturities), float_type="float64")
    assert canon == "AFNS5" and spec.M == 5 and spec.L == 2
    p, Omega, Phi = _afns5_params(spec)
    # oracle: generic Kalman with the AFNS Z and the adjustment folded into data
    Z = np.asarray(afns_loadings(jnp.asarray(p[0:2]), jnp.asarray(maturities), 5))
    adj = np.asarray(yield_adjustment(jnp.asarray(p[0:2]), jnp.asarray(Omega),
                                      jnp.asarray(maturities), 5))
    want = oracle.kalman_filter_loglik(Z, Phi, p[18:23], Omega, p[2],
                                       yields_panel - adj[:, None])
    got = float(get_loss(spec, jnp.asarray(p), jnp.asarray(yields_panel)))
    np.testing.assert_allclose(got, want, rtol=1e-8)


def test_afns5_predict_and_forecast(maturities, yields_panel):
    spec, _ = create_model("AFNS5", tuple(maturities), float_type="float64")
    p, *_ = _afns5_params(spec)
    ext = np.concatenate([yields_panel, np.full((len(maturities), 5), np.nan)], axis=1)
    res = predict(spec, jnp.asarray(p), jnp.asarray(ext))
    assert res["factors"].shape == (5, ext.shape[1])
    assert res["states"].shape == (2, ext.shape[1])
    assert np.all(np.isfinite(np.asarray(res["preds"])))


def test_afns3_param_count(maturities):
    spec, _ = create_model("AFNS3", tuple(maturities), float_type="float64")
    # γ(1) + σ²(1) + chol(6) + δ(3) + Φ(9) = 20
    assert spec.n_params == 20 and spec.M == 3


def test_afns3_yield_adjustment_matches_cdr_closed_form(maturities):
    """The quadrature yield adjustment must match the independently-derived
    Christensen–Diebold–Rudebusch closed form (VERDICT round 1, item 7) —
    the oracle writes B(s) from the model primitives, so a sign error in
    _price_loadings cannot cancel on both sides.  Full (non-diagonal) Ω."""
    rng = np.random.default_rng(5)
    lam = 0.47
    gamma = jnp.asarray([np.log(lam - 1e-2)])
    C = np.tril(0.02 * rng.standard_normal((3, 3))) + np.diag([0.1, 0.12, 0.15])
    Omega = C @ C.T  # full PSD covariance exercises every cross term
    want = oracle.afns3_yield_adjustment_cdr(lam, Omega, np.asarray(maturities))

    got64 = np.asarray(yield_adjustment(gamma, jnp.asarray(Omega),
                                        jnp.asarray(maturities), 3))
    got1024 = np.asarray(yield_adjustment(gamma, jnp.asarray(Omega),
                                          jnp.asarray(maturities), 3,
                                          quad_points=1024))
    # trapezoid error is O(h^2): observed ~1e-4 rel at 64 points shrinking
    # ~256x by 1024 points — converging to the closed form, as it must
    np.testing.assert_allclose(got64, want, rtol=2e-4, atol=1e-10)
    np.testing.assert_allclose(got1024, want, rtol=1e-6, atol=1e-12)
