"""Golden fixture for the legacy CSV export layout (VERDICT round 1,
missing #3).

The expected arrays below are written BY HAND from reading the reference's
export algorithm (/root/reference/src/databaseoperations/databaseoperations.jl:
391-661 + the digits=3 rounding at :251-255) — NOT produced by running the
library — so a silent drift in column order, target-index convention
(target = origin + h, h = 1..H), sorting (stable by origin then target for
the wide tables, by origin for params), or rounding would fail here even
though roundtrip tests still pass.

Byte-level note: the reference writes floats with Julia's writedlm
shortest-roundtrip repr while this repo uses numpy %.18g — a documented
writer difference.  The contract checked here is the numeric content and
layout, parsed back exactly (values carry 3-decimal rounding, so both
writers print them losslessly).
"""

import os

import numpy as np

from yieldfactormodels_jl_tpu.persistence import database as db


def _results(P, F, S, FL1, FL2):
    return {"preds": P, "factors": F, "states": S,
            "factor_loadings_1": FL1, "factor_loadings_2": FL2}


def test_legacy_export_matches_hand_derived_fixture(tmp_path):
    base = os.path.join(str(tmp_path), "db", "forecasts_expanding.sqlite3")
    H = 2  # forecast horizon: last H columns are saved

    # task 7 saved FIRST, task 5 second — export must still emit 5 before 7
    # values chosen to exercise round-half-even at 3 decimals:
    #   1.23456 -> 1.235 ; 0.0625 -> 0.062 (exact half, rounds even)
    P7 = np.array([[9.0, 1.23456, 2.0005],
                   [9.0, -4.44449, 2.0015]])     # (K=2, T=3); last H=2 kept
    F7 = np.array([[9.0, 0.1, 0.2]])
    S7 = np.array([[9.0, 0.3, 0.4]])
    FL1_7 = np.array([[9.0, 0.5, 0.6]])
    FL2_7 = np.array([[9.0, 0.7, 0.8]])
    params7 = np.array([0.123456789, -1.0])      # params are NOT rounded

    P5 = np.array([[9.0, 10.5, 11.25],
                   [9.0, -0.125, 0.0625]])
    F5 = np.array([[9.0, 1.0, 2.0]])
    S5 = np.array([[9.0, 3.0, 4.0]])
    FL1_5 = np.array([[9.0, 5.0, 6.0]])
    FL2_5 = np.array([[9.0, 7.0, 8.0]])
    params5 = np.array([42.0, 0.000123456])

    for task, (P, F, S, FL1, FL2, pa) in (
            (7, (P7, F7, S7, FL1_7, FL2_7, params7)),
            (5, (P5, F5, S5, FL1_5, FL2_5, params5))):
        db.save_oos_forecast_sharded(base, "NS", "1", "expanding", task,
                                     _results(P, F, S, FL1, FL2),
                                     loss=-1.0, params=pa, forecast_horizon=H)
    merged = db.merge_forecast_shards(base, task_ids=[7, 5])

    folder = str(tmp_path)
    paths = {
        "forecasts": db._export_wide(merged, folder, "NS", "1", [7, 5],
                                     "expanding", "preds", "forecasts"),
        "fitted_params": db._export_params(merged, folder, "NS", "1", [7, 5],
                                           "expanding"),
        "fl1": db._export_wide(merged, folder, "NS", "1", [7, 5],
                               "expanding", "fl1", "fl1"),
    }

    # ---- hand-derived expectations (reference algorithm on paper) ----
    # forecasts: rows (origin, origin+h, P[:, h-1]...) for h = 1..H, per
    # task, then stably sorted by target then origin (net: origin-major).
    # Saved preds are round.(·, digits=3) of the last H columns.
    want_forecasts = np.array([
        [5.0, 6.0, 10.5,   -0.125],
        [5.0, 7.0, 11.25,   0.062],   # 0.0625 -> 0.062 (half-even)
        [7.0, 8.0, 1.235,  -4.444],   # 1.23456 -> 1.235; -4.44449 -> -4.444
        [7.0, 9.0, 2.001,   2.002],   # 2.0005 is 2.000500...056 in binary
                                      # -> 2.001 (not a true half; the exact
                                      # half-even case is 0.0625 -> 0.062)
    ])
    got = np.loadtxt(paths["forecasts"], delimiter=",")
    np.testing.assert_array_equal(got, want_forecasts)

    # fitted_params: (origin, params...) sorted by origin; params unrounded
    # (the reference's digits=6 rounding is commented out, :250)
    want_params = np.array([
        [5.0, 42.0, 0.000123456],
        [7.0, 0.123456789, -1.0],
    ])
    got_p = np.loadtxt(paths["fitted_params"], delimiter=",")
    np.testing.assert_array_equal(got_p, want_params)

    # fl1: same wide layout as forecasts, 3-decimal rounded
    want_fl1 = np.array([
        [5.0, 6.0, 5.0],
        [5.0, 7.0, 6.0],
        [7.0, 8.0, 0.5],
        [7.0, 9.0, 0.6],
    ])
    got_fl1 = np.loadtxt(paths["fl1"], delimiter=",")
    np.testing.assert_array_equal(got_fl1, want_fl1)

    # file naming contract (databaseoperations.jl legacy path helpers)
    assert paths["forecasts"].endswith(
        "NS__thread_id__1__expanding_window_forecasts.csv")
    assert paths["fitted_params"].endswith(
        "NS__thread_id__1__expanding_window_fitted_params.csv")


def test_read_all_task_params_roundtrip(tmp_path):
    """Bulk snapshot-loading read (one query, one deser pass) returns exactly
    what the per-task reads return — and params survive unrounded."""
    base = os.path.join(str(tmp_path), "db", "forecasts_expanding.sqlite3")
    dummy = np.zeros((1, 2))
    results = _results(dummy, dummy, dummy, dummy, dummy)
    params = {3: np.array([0.123456789, -1.0, 42.0]),
              9: np.array([7.5, 0.000123456, -0.25])}
    for task, p in params.items():
        db.save_oos_forecast_sharded(base, "NS", "1", "expanding", task,
                                     results, loss=-1.0, params=p,
                                     forecast_horizon=1)
    merged = db.merge_forecast_shards(base, task_ids=sorted(params))

    got = db.read_all_task_params(merged)
    assert sorted(got) == [3, 9]
    for task, p in params.items():
        np.testing.assert_array_equal(got[task], p)  # NOT rounded (ser/deser)
        np.testing.assert_array_equal(got[task],
                                      db.read_task_params(merged, task))
    assert db.read_all_task_params(os.path.join(str(tmp_path), "nope.sqlite3")) == {}
