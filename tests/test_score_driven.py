"""Score-driven filter golden tests vs the NumPy oracle (analytic inner score)."""

import jax
import jax.numpy as jnp
import numpy as np

from tests import oracle
from yieldfactormodels_jl_tpu import create_model, get_loss, predict
from yieldfactormodels_jl_tpu.models import score_driven as SD
from yieldfactormodels_jl_tpu.models.params import unpack_msed


def _lambda_params(spec, random_walk=False):
    """[A(1), B(1)?, ω(1), δ(3), Φ col-major(9)] constrained."""
    vals = [1e-3]
    if not random_walk:
        vals.append(0.97)
    vals.append(np.log(0.5))          # omega = gamma fixed point
    vals.extend([0.3, -0.1, 0.05])    # delta
    Phi = np.array([[0.95, 0.02, 0.0], [0.01, 0.9, 0.03], [0.0, 0.02, 0.85]])
    vals.extend(Phi.T.reshape(-1))    # column-major vec
    p = np.asarray(vals)
    assert p.shape[0] == spec.n_params
    return p, Phi


def _struct(p, random_walk):
    if random_walk:
        return {"A": np.array([p[0]]), "B": None, "omega": np.array([p[1]]),
                "delta": p[2:5], "Phi": p[5:14].reshape(3, 3).T}
    return {"A": np.array([p[0]]), "B": np.array([p[1]]), "omega": np.array([p[2]]),
            "delta": p[3:6], "Phi": p[6:15].reshape(3, 3).T}


def test_unpack_msed_layout(maturities):
    spec, _ = create_model("SD-NS", tuple(maturities), float_type="float64")
    p, Phi = _lambda_params(spec)
    mp = unpack_msed(spec, jnp.asarray(p))
    np.testing.assert_allclose(mp.Phi, Phi, rtol=1e-12)
    np.testing.assert_allclose(mp.mu, (np.eye(3) - Phi) @ p[3:6], rtol=1e-12)
    np.testing.assert_allclose(mp.nu, (1 - p[1]) * p[2], rtol=1e-12)


def test_inner_score_matches_analytic(maturities, rng):
    """jax.grad of the inner objective == hand-derived gradient (λ model)."""
    spec, _ = create_model("SD-NS", tuple(maturities), float_type="float64")
    gamma = jnp.asarray([np.log(0.4)])
    beta = jnp.asarray([5.0, -1.0, 0.5])
    y = jnp.asarray(rng.standard_normal(len(maturities)) + 5.0)
    got = np.asarray(SD._score(spec, gamma, beta, y))
    want = oracle._dns_score(np.asarray(gamma), np.asarray(beta), np.asarray(y), maturities)
    np.testing.assert_allclose(got, want, rtol=1e-9)


def _filter_parity(maturities, yields_panel, code, random_walk, scale_grad):
    spec, _ = create_model(code, tuple(maturities), float_type="float64")
    p, _ = _lambda_params(spec, random_walk)
    res = predict(spec, jnp.asarray(p), jnp.asarray(yields_panel))
    want_preds = oracle.msed_lambda_filter(
        _struct(p, random_walk), maturities, yields_panel,
        scale_grad=scale_grad, forget_factor=spec.forget_factor,
    )
    np.testing.assert_allclose(np.asarray(res["preds"]), want_preds, rtol=1e-6, atol=1e-9)
    want_loss = oracle.msed_loss_from_preds(want_preds, yields_panel)
    got_loss = float(get_loss(spec, jnp.asarray(p), jnp.asarray(yields_panel)))
    np.testing.assert_allclose(got_loss, want_loss, rtol=1e-6)


def test_msed_lambda_filter_parity(maturities, yields_panel):
    _filter_parity(maturities, yields_panel, "SD-NS", False, False)


def test_msed_lambda_rw_parity(maturities, yields_panel):
    _filter_parity(maturities, yields_panel, "RWSD-NS", True, False)


def test_msed_lambda_scaled_parity(maturities, yields_panel):
    _filter_parity(maturities, yields_panel, "SSD-NS", False, True)


def test_masked_prefix_equals_truncation(maturities, yields_panel):
    spec, _ = create_model("1SSD-NNS", tuple(maturities), float_type="float64")
    rng = np.random.default_rng(3)
    p = np.zeros(spec.n_params)
    p[0:2] = 1e-4            # A unique (scalar dynamics: 2 uniques)
    p[2:4] = 0.98            # B unique
    p[4:22] = rng.standard_normal(18) / 10   # omega (net params)
    p[22:25] = [0.3, -0.1, 0.05]
    Phi = np.diag([0.95, 0.9, 0.85])
    p[25:34] = Phi.T.reshape(-1)
    full = jnp.asarray(yields_panel)
    lo, hi = 12, 55
    masked = float(SD.get_loss(spec, jnp.asarray(p), full, start=lo, end=hi))
    trunc = float(SD.get_loss(spec, jnp.asarray(p), full[:, lo:hi]))
    np.testing.assert_allclose(masked, trunc, rtol=1e-8)


def test_partial_nan_observed_column_poisons_loss(maturities, yields_panel):
    spec, _ = create_model("SD-NS", tuple(maturities), float_type="float64")
    p, _ = _lambda_params(spec)
    bad = yields_panel.copy()
    bad[5, 10] = np.nan  # first maturity finite ⇒ still "observed"
    got = float(get_loss(spec, jnp.asarray(p), jnp.asarray(bad)))
    assert got == -np.inf


def test_outer_gradient_through_inner_score(maturities, yields_panel):
    """Second-order AD: outer grad of the loss through the per-step inner grad.

    With ``detach_inner_beta=False`` the gradient is exact AD of the loss and
    must match finite differences.  With the default (reference parity,
    filter.jl:175 detaches β) it must differ — that drop of β's sensitivity is
    intentional reference behavior, not an AD bug.
    """
    import dataclasses

    spec, _ = create_model("SSD-NS", tuple(maturities), float_type="float64")
    p, _ = _lambda_params(spec)
    spec_exact = dataclasses.replace(spec, detach_inner_beta=False)

    def loss_exact(pv):
        return SD.get_loss(spec_exact, pv, jnp.asarray(yields_panel))

    def loss_ref(pv):
        return SD.get_loss(spec, pv, jnp.asarray(yields_panel))

    g_exact = np.asarray(jax.grad(loss_exact)(jnp.asarray(p)))
    g_ref = np.asarray(jax.grad(loss_ref)(jnp.asarray(p)))
    assert np.all(np.isfinite(g_exact)) and np.all(np.isfinite(g_ref))
    for i in (0, 2, 5):
        e = np.zeros_like(p)
        e[i] = 1e-6
        fd = (float(loss_exact(jnp.asarray(p + e))) - float(loss_exact(jnp.asarray(p - e)))) / 2e-6
        np.testing.assert_allclose(g_exact[i], fd, rtol=2e-3, atol=1e-8)
    # reference-parity gradient intentionally differs from exact AD
    assert not np.allclose(g_ref, g_exact, rtol=1e-3)


# ---------------------------------------------------------------------------
# neural-family end-to-end golden tests (VERDICT round 1, item 4): the
# reference's own driver model is 1SSD-NNS (/root/reference/test.jl:22)
# ---------------------------------------------------------------------------

def _neural_params(spec, rng, random_walk=False):
    """Constrained params for a scalar-dynamics neural code + the oracle
    struct with A/B expanded through the scalar duplicator ([0]*9+[1]*9 —
    replicated here from mseneural.jl:33-51, NOT read from the spec)."""
    a_u = np.array([2e-4, 1e-4])
    b_u = np.array([0.97, 0.95])
    omega = rng.standard_normal(18) / 10
    delta = np.array([0.3, -0.1, 0.05])
    Phi = np.array([[0.95, 0.02, 0.0], [0.01, 0.9, 0.03], [0.0, 0.02, 0.85]])
    vals = list(a_u)
    if not random_walk:
        vals.extend(b_u)
    vals.extend(omega)
    vals.extend(delta)
    vals.extend(Phi.T.reshape(-1))
    p = np.asarray(vals)
    assert p.shape[0] == spec.n_params
    struct = oracle.neural_struct_from_flat(p, random_walk=random_walk)
    return p, struct


def _neural_parity(maturities, yields_panel, code, random_walk, scale_grad,
                   transform_bool):
    spec, _ = create_model(code, tuple(maturities), float_type="float64")
    rng = np.random.default_rng(7)
    p, struct = _neural_params(spec, rng, random_walk)
    data = yields_panel[:, :50]
    res = predict(spec, jnp.asarray(p), jnp.asarray(data))
    want_preds = oracle.msed_neural_filter(
        struct, maturities, data, transform_bool,
        scale_grad=scale_grad, forget_factor=spec.forget_factor)
    np.testing.assert_allclose(np.asarray(res["preds"]), want_preds,
                               rtol=1e-6, atol=1e-9)
    want_loss = oracle.msed_loss_from_preds(want_preds, data)
    got_loss = float(get_loss(spec, jnp.asarray(p), jnp.asarray(data)))
    np.testing.assert_allclose(got_loss, want_loss, rtol=1e-6)


def test_msed_neural_driver_model_parity(maturities, yields_panel):
    """1SSD-NNS — the reference driver's model (test.jl:22): scalar dynamics,
    EWMA-scaled score, transformed loadings."""
    _neural_parity(maturities, yields_panel, "1SSD-NNS",
                   random_walk=False, scale_grad=True, transform_bool=True)


def test_msed_neural_plain_parity(maturities, yields_panel):
    _neural_parity(maturities, yields_panel, "1SD-NNS",
                   random_walk=False, scale_grad=False, transform_bool=True)


def test_msed_neural_anchored_parity(maturities, yields_panel):
    """-Anchored variant: no affine detrend in the shape transforms
    (neural_network_transform.jl:61-100)."""
    _neural_parity(maturities, yields_panel, "1SD-NNS-Anchored",
                   random_walk=False, scale_grad=False, transform_bool=False)


def test_msed_neural_rw_parity(maturities, yields_panel):
    """Random-walk dynamics: B empty, gamma transition is identity."""
    _neural_parity(maturities, yields_panel, "1RWSD-NNS",
                   random_walk=True, scale_grad=False, transform_bool=True)
